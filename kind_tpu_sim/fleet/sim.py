"""The fleet driver: trace -> router -> replicas -> SLO report.

One virtual-clock loop glues the pieces together: arrivals due at a
boundary enter the router (or shed), the router places its queue by
policy, every replica advances through the boundary's window,
completions stream into the SLO tracker and the per-request
completion log, and the autoscaler gets one observation per
evaluation interval. Chaos events (replica preemption / restore)
fire at planned virtual times and displaced requests requeue at the
router — the same loop the `fleet run` CLI, the bench fleet section,
and the chaos fleet scenarios all drive.

Two execution strategies cover one semantics (docs/PERFORMANCE.md
"The event core"): the plain loop steps EVERY tick boundary; the
event core (``KIND_TPU_SIM_FLEET_EVENT_CORE``, default on) steps
only the boundaries where an event lands — arrivals, chaos, replica
slot events (closed-form, fleet/events.py), warm-ups, probe
deadlines, autoscaler evaluations — and advances the clock through
the skipped boundaries by the identical tick-sized float additions,
so wall time scales with event count while reports stay
byte-identical with the core on or off.

Determinism: the loop consumes no wall time, no entropy, and iterates
replicas in id order; the completion log is emitted sorted by
(finish_s, request_id). Two runs of the same (trace, config) are
byte-identical — `fleet run --seed 7` twice diffs clean.
"""

from __future__ import annotations

import dataclasses
import warnings
import zlib
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence

from kind_tpu_sim import metrics
from kind_tpu_sim.analysis import knobs
from kind_tpu_sim.health import DetectorConfig, FailureDetector
from kind_tpu_sim.parallel import collectives
from kind_tpu_sim.fleet.autoscaler import (
    Autoscaler,
    AutoscalerConfig,
    resolve_warmup_s,
)
from kind_tpu_sim.fleet.disagg import (
    DisaggConfig,
    KvHandoff,
    kv_transfer_s,
    calibrated_sim_config,
)
from kind_tpu_sim.fleet.columnar import (
    COLUMNAR_MIN_REPLICAS,
    FleetColumns,
    resolve_columnar,
)
from kind_tpu_sim.fleet.events import (
    LANE_ARRIVAL,
    LANE_AUTOSCALER,
    LANE_CHAOS,
    LANE_COMPLETION,
    LANE_INTEGRITY_AUDIT,
    LANE_KV_TRANSFER,
    LANE_MODEL_SWAP,
    DueSet,
    EventHeap,
    resolve_event_core,
)
from kind_tpu_sim.fleet.loadgen import TraceRequest, VirtualClock
from kind_tpu_sim.fleet.overload import (
    OverloadConfig,
    OverloadState,
    request_tier,
)
from kind_tpu_sim.fleet.router import (
    ReplicaCompletion,
    Router,
    SimReplica,
    SimReplicaConfig,
)
from kind_tpu_sim.fleet.slo import SloPolicy, SloTracker
from kind_tpu_sim.fleet.tenancy import (
    TenancyConfig,
    TenancyState,
    tenant_of,
)
from kind_tpu_sim.fleet.training import (
    TrainingConfig,
    TrainingTenant,
)

TICK_ENV = knobs.FLEET_TICK_S
DEFAULT_TICK_S = 0.01
FF_ENV = knobs.FLEET_FF


def resolve_tick_s(value: Optional[float] = None) -> float:
    """Explicit value > env (KIND_TPU_SIM_FLEET_TICK_S) > 0.01."""
    if value is not None:
        return float(value)
    return float(knobs.get(TICK_ENV))


_EVAL_TICKS_WARNED = False


def _warn_eval_every_ticks() -> None:
    """One-shot deprecation notice for the retired tick-count
    cadence knob (the window opened in PR 8; docs/FLEET.md)."""
    global _EVAL_TICKS_WARNED
    if _EVAL_TICKS_WARNED:
        return
    _EVAL_TICKS_WARNED = True
    warnings.warn(
        "FleetConfig.eval_every_ticks is retired: it couples the "
        "control-loop cadence to the tick width. Use eval_every_s "
        "(virtual seconds, snapped to the tick grid) instead; the "
        "value you set is honored as eval_every_ticks * tick_s.",
        DeprecationWarning, stacklevel=3)


def resolve_fast_forward(value: Optional[bool] = None) -> bool:
    """Explicit value > env (KIND_TPU_SIM_FLEET_FF) > on.

    Fast-forward skips the per-tick work across PROVABLY idle gaps
    (nothing in flight, nothing due before the next arrival/chaos
    event) while advancing the virtual clock through the identical
    sequence of tick-sized float additions — so reports stay
    byte-identical with it on or off, and multi-hour diurnal (or
    N-cell globe) sims stop paying wall time per empty tick. Set
    ``KIND_TPU_SIM_FLEET_FF=0`` to force the plain loop."""
    if value is not None:
        return bool(value)
    return bool(knobs.get(FF_ENV))


def resolve_audit_frac(value: Optional[float] = None) -> float:
    """Explicit value > env (KIND_TPU_SIM_SDC_AUDIT_FRAC) > 0.0.

    The sampled duplicate-compute integrity audit lane (docs/SDC.md):
    this fraction of served requests re-execute on a second replica
    and their token fingerprints are compared. 0.0 (the default)
    keeps the lane off and every pre-SDC replay byte-identical."""
    if value is not None:
        return max(0.0, min(1.0, float(value)))
    return max(0.0, min(1.0, float(knobs.get(knobs.SDC_AUDIT_FRAC))))


@dataclasses.dataclass(frozen=True)
class ChaosEvent:
    """A fleet-level fault: ``preempt`` displaces a replica's whole
    load (chaos.py derives these from a seeded FaultPlan); ``restore``
    heals it. With a scheduler-backed fleet (``FleetConfig.sched``)
    three node-level actions join: ``node_drain`` cordons node index
    ``target`` and evicts its gangs (replicas preempt, reschedule,
    and warm back up elsewhere), ``node_fail`` breaks the node
    outright, ``node_restore`` heals it.

    GRAY actions (docs/HEALTH.md) degrade without killing: ``slow``
    inflates replica ``target``'s service times by factor ``param``
    (the slow_replica fault kind), ``unslow`` restores it;
    ``link_degrade`` sets ICI domain index ``target``'s slowest-link
    bandwidth factor to ``param`` (scheduler-backed fleets only —
    every replica placed there inflates by the modeled collective
    share, parallel/collectives.ici_slowdown), ``link_restore``
    heals the domain.

    SDC actions (docs/SDC.md) make the OUTPUT the casualty:
    ``sdc_chip`` turns replica ``target``'s chip defective (it
    corrupts fraction ``param`` of its completions while every
    timing stays nominal — and unlike the windowed faults above
    there is NO paired heal event; only integrity quarantine stops
    it); ``sdc_train_chip`` plants the defect on a chip of training
    gang ``target`` instead; ``domain_fault`` fails every node of
    one rack/power failure domain at once (scheduler-backed fleets
    with ``FleetSchedConfig.rack_pods``), ``domain_restore`` heals
    the domain."""

    at_s: float
    action: str   # preempt | restore | node_* | slow | unslow | link_*
    target: int   # replica id, node index, or ICI domain index
    param: float = 0.0  # slow factor / link bandwidth factor

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass(frozen=True)
class FleetSchedConfig:
    """Scheduler-backed placement for the fleet (docs/SCHED.md).

    When set on :class:`FleetConfig`, replicas stop materializing
    out of thin air: every replica is a gang placed by
    :class:`kind_tpu_sim.sched.ClusterScheduler` on an inventory of
    ``pods``, and a scale-up's time-to-routable becomes queue wait +
    placement (``bind_s``) + warm-up instead of the flat constant —
    never less than the flat-warmup baseline, by construction.
    ``replica_topology`` is the slice each serving replica occupies
    (default: one whole v5e host); serving replicas run at
    ``priority`` (above batch fill so chaos/batch experiments can
    exercise preemption)."""

    pods: tuple = (("tpu-v5-lite-podslice", "4x8"),)
    policy: str = "ici"
    bind_s: float = 0.05
    replica_accelerator: str = "tpu-v5-lite-podslice"
    replica_topology: str = "2x4"
    priority: int = 10
    # the inventory's topology.kubernetes.io/zone label — one fleet
    # is one cell in one zone; the globe layer stamps each cell's
    # inventory with its owning zone (docs/GLOBE.md)
    zone: str = "zone-a"
    # share of a replica's service time spent in ICI collectives —
    # the Amdahl knob the degraded-link slowdown model
    # (parallel/collectives.ici_slowdown) applies to replicas placed
    # on a degraded domain, and to their warm-up on rebind
    ici_fraction: float = 0.35
    # correlated-failure grouping (docs/SDC.md): every rack_pods
    # consecutive pods share one rack/power failure_domain label —
    # the blast radius domain_fault chaos takes out in one draw.
    # None (the default) leaves the inventory ungrouped and every
    # pre-SDC report byte-identical.
    rack_pods: Optional[int] = None

    def as_dict(self) -> dict:
        out = {
            "pods": [list(p) for p in self.pods],
            "policy": self.policy,
            "bind_s": self.bind_s,
            "replica_accelerator": self.replica_accelerator,
            "replica_topology": self.replica_topology,
            "priority": self.priority,
            "ici_fraction": self.ici_fraction,
            "zone": self.zone,
        }
        if self.rack_pods is not None:
            out["rack_pods"] = self.rack_pods
        return out


@dataclasses.dataclass(frozen=True)
class FleetConfig:
    replicas: int = 2
    policy: str = "round-robin"
    tick_s: Optional[float] = None     # None -> resolve_tick_s()
    max_queue: int = 1024              # router admission bound
    max_virtual_s: float = 600.0       # runaway-loop backstop
    autoscale: bool = False
    # control-loop cadence (autoscaler evaluations AND the overload
    # layer's brownout evaluations): eval_every_s is virtual seconds
    # between evaluations, snapped to the tick grid (default: 10
    # ticks' worth, which keeps pre-eval_every_s replays
    # byte-identical). eval_every_ticks is RETIRED (PR 8 opened the
    # deprecation window): setting it still works — it routes
    # through eval_every_s as ticks * tick_s — but emits a one-shot
    # DeprecationWarning; it couples the real-time cadence to the
    # tick width, which is exactly the bug eval_every_s fixed.
    # contractlint: ok(drift) -- retired alias: reports carry the cadence as eval_every_s
    eval_every_ticks: Optional[int] = None
    eval_every_s: Optional[float] = None
    slo: SloPolicy = SloPolicy(ttft_s=0.5, e2e_s=2.0)
    sim: SimReplicaConfig = SimReplicaConfig()
    autoscaler: AutoscalerConfig = AutoscalerConfig()
    sched: Optional[FleetSchedConfig] = None
    # gray-failure detection (docs/HEALTH.md): a DetectorConfig turns
    # on the per-replica service-time detector — quarantined replicas
    # leave the routing set, get probed, and (scheduler-backed) have
    # their gang migrated off the suspect hardware
    health: Optional[DetectorConfig] = None
    # overload containment (docs/OVERLOAD.md): an OverloadConfig
    # turns on client retry budgets, hedged requests with
    # first-completion-wins cancellation, per-replica circuit
    # breakers under the routing policies, and the brownout ladder
    overload: Optional[OverloadConfig] = None
    # training tenancy (docs/TRAINING.md): long-running training
    # gangs co-scheduled UNDER serving on the same inventory under
    # strict priority — requires a scheduler-backed fleet (sched)
    training: Optional[TrainingConfig] = None
    # disaggregated prefill/decode serving (docs/DISAGG.md): a
    # DisaggConfig splits the fleet into phase pools with modeled
    # KV-cache handoff between them. None (the default) keeps every
    # replica unified and every historical replay byte-identical.
    disagg: Optional[DisaggConfig] = None
    # multi-tenant isolation (docs/TENANCY.md): a TenancyConfig turns
    # on per-tenant admission quotas, deficit-round-robin fair
    # queuing at the router, declared-tier brownout shedding,
    # per-tenant KV/prefix budgets, and the per-tenant SLO board.
    # None keeps the anonymous fleet and every historical replay
    # byte-identical.
    tenancy: Optional[TenancyConfig] = None
    # model zoo (docs/ZOO.md): a ZooConfig turns on multi-model
    # serving — per-replica warm-pool state, modeled weight-load
    # swap cost on the LANE_MODEL_SWAP event lane, model-aware
    # warm-first routing, and the per-model SLO board. None keeps
    # the single-model fleet and every historical replay
    # byte-identical.
    zoo: Optional[object] = None
    # heterogeneous generations (docs/ZOO.md): the accelerator
    # generation names (fleet/calibration/<gen>.json) replicas price
    # against, cycled over replica ids — ("v5e", "v5p") alternates.
    # A scheduler-backed fleet instead derives its single generation
    # from FleetSchedConfig.replica_accelerator. None keeps the
    # hand-tuned SimReplicaConfig defaults and every historical
    # replay byte-identical.
    generations: Optional[tuple] = None
    # model-placement lever (docs/ZOO.md, docs/TUNE.md): force which
    # generation's warm set carries the zoo's largest model (the
    # tune `large_model_gen` dimension). None keeps the default
    # largest-fitting-model placement. Ignored unless zoo is set;
    # inert when the named generation is not in the cycle.
    zoo_large_model_gen: Optional[str] = None
    # idle-gap fast-forward (None -> resolve_fast_forward()). An
    # execution strategy, not workload config: reports are
    # byte-identical either way, so it deliberately stays OUT of
    # as_dict() — an ff-on and an ff-off run must diff clean.
    # contractlint: ok(drift) -- execution strategy: ff-on vs ff-off reports must diff clean
    fast_forward: Optional[bool] = None
    # event-heap core (None -> resolve_event_core(), default on).
    # Same contract as fast_forward: an execution strategy that must
    # diff clean on vs off, so it stays OUT of as_dict() too.
    # contractlint: ok(drift) -- execution strategy: heap-core on vs off reports must diff clean
    event_core: Optional[bool] = None
    # integrity audit lane (docs/SDC.md): the fraction of served
    # requests re-executed on a second replica with fingerprint
    # comparison (None -> resolve_audit_frac(), default 0 = off).
    # Audit copies are REAL occupancy — they take replica slots, so
    # the integrity/throughput trade-off is priced, not free.
    audit_frac: Optional[float] = None
    # columnar replica state (None -> resolve_columnar(), default
    # on): keeps the analytic fleet's wake scans / tick fan-out /
    # least-outstanding routing in numpy struct-of-arrays
    # (fleet/columnar.py). Same contract again: an execution
    # strategy, byte-identical on or off, so it stays OUT of
    # as_dict().
    # contractlint: ok(drift) -- execution strategy: columnar on vs off reports must diff clean
    columnar: Optional[bool] = None

    def as_dict(self) -> dict:
        out = {
            "replicas": self.replicas,
            "policy": self.policy,
            "tick_s": resolve_tick_s(self.tick_s),
            "max_queue": self.max_queue,
            "max_virtual_s": self.max_virtual_s,
            "autoscale": self.autoscale,
            "slo": {k: v for k, v in
                    dataclasses.asdict(self.slo).items()
                    if v is not None},
            "sim": self.sim.as_dict(),
        }
        if self.eval_every_s is not None:
            out["eval_every_s"] = self.eval_every_s
        if self.autoscale:
            out["autoscaler"] = dataclasses.asdict(self.autoscaler)
        if self.sched is not None:
            out["sched"] = self.sched.as_dict()
        if self.health is not None:
            out["health"] = self.health.as_dict()
        if self.overload is not None:
            out["overload"] = self.overload.as_dict()
        if self.training is not None:
            out["training"] = self.training.as_dict()
        if self.disagg is not None:
            out["disagg"] = self.disagg.as_dict()
        if self.tenancy is not None:
            out["tenancy"] = self.tenancy.as_dict()
        if self.zoo is not None:
            out["zoo"] = self.zoo.as_dict()
        if self.generations is not None:
            out["generations"] = list(self.generations)
        if self.zoo_large_model_gen is not None:
            out["zoo_large_model_gen"] = self.zoo_large_model_gen
        if self.audit_frac is not None:
            out["audit_frac"] = self.audit_frac
        return out


class FleetSim:
    """One fleet run. ``replica_factory(replica_id)`` builds a
    replica (default: a SimReplica with ``cfg.sim``); engine-backed
    fleets pass a factory closing over shared params — constructing
    extra ServingEngines is cheap because the jitted kernels are
    module-cached per ModelConfig."""

    def __init__(self, cfg: FleetConfig,
                 trace: Sequence[TraceRequest],
                 replica_factory: Optional[Callable[[int], object]]
                 = None,
                 chaos_events: Sequence[ChaosEvent] = (),
                 clock: Optional[VirtualClock] = None):
        self.cfg = cfg
        self.clock = clock or VirtualClock()
        self.trace = sorted(trace,
                            key=lambda r: (r.arrival_s, r.request_id))
        # disaggregated serving (docs/DISAGG.md): phase-split pools
        # with modeled KV handoff. Incompatible with scheduler-backed
        # placement by design — gang rebind/migration would need the
        # whole phase lifecycle threaded through sched, and the
        # disagg questions (pool ratios, transfer cost, pool-loss
        # survival) don't need it.
        self._disagg = (cfg.disagg
                        if cfg.disagg is not None
                        and cfg.disagg.enabled else None)
        self._cost = None
        self._disagg_sim_cfg = cfg.sim
        if self._disagg is not None:
            from kind_tpu_sim.fleet.costmodel import (
                CostModel,
                kv_bytes_per_token,
                load_calibration,
            )

            dis = self._disagg
            if cfg.sched is not None:
                raise ValueError(
                    "FleetConfig.disagg is incompatible with a "
                    "scheduler-backed fleet (FleetConfig.sched)")
            want = dis.prefill_replicas + dis.decode_replicas
            if cfg.replicas != want:
                raise ValueError(
                    f"FleetConfig.replicas={cfg.replicas} must equal "
                    f"the disagg pool sum {dis.prefill_replicas}+"
                    f"{dis.decode_replicas}={want}")
            if replica_factory is not None:
                raise ValueError(
                    "a disagg fleet builds its own phased replicas; "
                    "replica_factory is not supported")
            cal = load_calibration()
            self._cost = CostModel(cal)
            self._kv_per_tok = kv_bytes_per_token(
                cal["geometry"], dis.dtype)
            if dis.calibrated:
                self._disagg_sim_cfg = calibrated_sim_config(
                    cal, dis.dtype,
                    max_slots=cfg.sim.max_slots,
                    max_queue=cfg.sim.max_queue,
                    prefix_cache_entries=cfg.sim
                    .prefix_cache_entries)
        # model zoo / per-generation pricing (docs/ZOO.md): a
        # ZooConfig and/or a generations tuple makes every replica a
        # calibrated SimReplica priced off its generation's
        # fleet/calibration/<gen>.json; a scheduler-backed fleet
        # derives its (single) generation from the accelerator label
        # its gangs request — FleetSchedConfig.replica_accelerator,
        # finally consumed end to end. Both default to None, keeping
        # every historical replay byte-identical.
        self._zoo = cfg.zoo
        self._generations: Optional[List[str]] = None
        self._gen_cals: Dict[str, dict] = {}
        self._gen_residents: Dict[str, str] = {}
        self._swap_heap = EventHeap()
        self._swap_log: List[dict] = []
        self._model_trackers: Dict[str, SloTracker] = {}
        if self._zoo is not None or cfg.generations is not None:
            from kind_tpu_sim.fleet import zoo as zoo_mod
            from kind_tpu_sim.fleet.costmodel import (
                generation_of_accelerator,
                load_generation,
            )

            if replica_factory is not None:
                raise ValueError(
                    "a zoo/generation fleet builds its own "
                    "calibrated replicas; replica_factory is not "
                    "supported")
            if self._disagg is not None:
                raise ValueError(
                    "FleetConfig.zoo/generations do not compose "
                    "with disagg phase pools yet (phase pools price "
                    "off the r05 anchor)")
            if cfg.sched is not None:
                gens = (generation_of_accelerator(
                    cfg.sched.replica_accelerator),)
            elif cfg.generations:
                gens = tuple(cfg.generations)
            else:
                gens = (zoo_mod.resolve_generation(),)
            self._gen_cycle = gens
            self._generations = [gens[i % len(gens)]
                                 for i in range(cfg.replicas)]
            self._gen_cals = {g: load_generation(g)
                              for g in sorted(set(gens))}
            if self._zoo is not None:
                uniq = sorted(set(gens))
                self._gen_residents = dict(zip(
                    uniq, zoo_mod.placements(
                        self._zoo, uniq,
                        large_model_gen=cfg.zoo_large_model_gen)))
        # NOTE: replica_factory stays None for zoo/generation fleets
        # so the columnar-eligibility check below still sees an
        # all-analytic fleet — calibrated replicas are plain
        # SimReplicas with closed-form next_due.
        self.factory = replica_factory or (
            lambda rid: SimReplica(rid, cfg.sim))
        if self._generations is not None:
            self.factory = self._make_gen_replica
        if self._disagg is not None:
            p = self._disagg.prefill_replicas
            self.replicas = [
                SimReplica(i, self._disagg_sim_cfg,
                           phase="prefill" if i < p else "decode")
                for i in range(cfg.replicas)]
        else:
            self.replicas = [self.factory(i)
                             for i in range(cfg.replicas)]
        self.health = (FailureDetector(cfg.health)
                       if cfg.health is not None else None)
        self.overload = (OverloadState(cfg.overload)
                         if cfg.overload is not None else None)
        # multi-tenancy (docs/TENANCY.md): quota buckets + the
        # weights/tiers the router's DRR and brownout read
        self.tenancy = (TenancyState(cfg.tenancy)
                        if cfg.tenancy is not None else None)
        self._tenant_trackers: Dict[str, SloTracker] = {}
        self.router = Router(self.replicas, policy=cfg.policy,
                             max_queue=cfg.max_queue,
                             health=self.health,
                             overload=self.overload,
                             disagg=self._disagg is not None,
                             tenancy=self.tenancy,
                             zoo=self._zoo is not None)
        for replica in self.replicas:
            self._install_tenant_caps(replica)
        if self.overload is not None:
            self.router.on_place = self._on_place
        # columnar mirror: engages only on all-analytic fleets (no
        # replica_factory means every replica is a SimReplica with a
        # closed-form next_due — disagg included); engine-backed
        # fleets keep the per-object paths
        self._cols: Optional[FleetColumns] = None
        if replica_factory is None and (
                cfg.columnar is True
                or (resolve_columnar(cfg.columnar)
                    and cfg.replicas >= COLUMNAR_MIN_REPLICAS)):
            self._cols = FleetColumns(self.replicas)
            self.router._columns = self._cols
        self.chaos_events = sorted(chaos_events,
                                   key=lambda e: (e.at_s, e.target))
        self.tracker = SloTracker(
            cfg.slo, track_itl=self._disagg is not None)
        self.autoscaler = (Autoscaler(cfg.autoscaler)
                           if cfg.autoscale
                           and self._disagg is None else None)
        # phase-pool autoscaling: each pool scales on its OWN signal
        # (TTFT breach -> prefill, ITL/queue-depth breach -> decode),
        # floored at its declared size
        self._pool_scalers: Optional[Dict[str, Autoscaler]] = None
        if self._disagg is not None and cfg.autoscale:
            dis = self._disagg
            self._pool_scalers = {
                "prefill": Autoscaler(dataclasses.replace(
                    cfg.autoscaler,
                    min_replicas=dis.prefill_replicas)),
                "decode": Autoscaler(dataclasses.replace(
                    cfg.autoscaler,
                    min_replicas=dis.decode_replicas)),
            }
        # KV transfers in flight between the pools: an EventHeap of
        # (deliver_at_s, LANE_KV_TRANSFER, seq, KvHandoff); the
        # kv_transfer_degrade chaos lever scales the link bandwidth
        # for transfers scheduled AFTER it fires
        self._kv_heap = EventHeap()
        self._kv_factor = 1.0
        self._prefill_done_ids: set = set()
        # hedge/failover-cancelled ids whose KV transfer is still on
        # the wire: the heap has no removal, so cancellation is lazy
        # — the handoff is dropped at delivery (globe/cell.py)
        self._kv_cancelled: set = set()
        self._kv_handoffs = 0
        self._kv_bytes_total = 0
        self._kv_transfer_s_total = 0.0
        # per-phase SLO windows driving the pool scalers
        self._recent_ttft = deque(maxlen=64)
        self._recent_itl = deque(maxlen=64)
        self.log: List[dict] = []
        # cell-embedding hook (docs/GLOBE.md): the globe driver sets
        # this to stream every completion entry out of the cell as it
        # lands (per-zone boards, front-door SLO windows). Called
        # with (entry_dict, ReplicaCompletion).
        self.on_complete: Optional[Callable] = None
        # recent attained-flags window: the autoscaler's SLO signal
        self._recent = deque(maxlen=64)
        self._next_replica_id = cfg.replicas
        # replicas paid for but not yet routable: an EventHeap of
        # (ready_at_s, LANE_AUTOSCALER, seq, (replica, reason))
        self._warming = EventHeap()
        # gang-evicted replicas awaiting rebind+warmup heal — only
        # populated on scheduler-backed fleets
        self._rebinding = EventHeap()
        self._draining: List = []
        self.preemptions = 0
        self.sched = None
        self._now = 0.0
        self._ticks = 0
        self._pending = deque(self.trace)
        self._fast_forward = resolve_fast_forward(cfg.fast_forward)
        self._event_core = resolve_event_core(cfg.event_core)
        # effective control-loop cadence in ticks: everything routes
        # through eval_every_s snapped to the grid. The RETIRED
        # eval_every_ticks still works (ticks * tick_s) but warns
        # once; unset, the cadence defaults to 10 ticks' worth.
        tick_s = resolve_tick_s(cfg.tick_s)
        if cfg.eval_every_s is not None:
            eval_every_s = cfg.eval_every_s
        elif cfg.eval_every_ticks is not None:
            _warn_eval_every_ticks()
            eval_every_s = cfg.eval_every_ticks * tick_s
        else:
            eval_every_s = 10 * tick_s
        self._eval_ticks = max(1, int(round(eval_every_s / tick_s)))
        # empty ticks skipped by fast-forward / boundaries skipped by
        # the event core — observability only, deliberately NOT in
        # the report (each mode on/off must diff clean)
        self.ff_skipped = 0
        self.ev_skipped = 0
        # wake-scan backoff: when a scan concludes "step the next
        # boundary anyway", hold off re-scanning for a few boundaries
        # (doubling, capped). Stepping a boundary is ALWAYS
        # semantics-identical to the plain loop, so this is a pure
        # cost heuristic — dense regions stop paying scan overhead
        # per tick, sparse regions amortize one scan over the whole
        # jump. Deterministic: a function of sim state only.
        self._scan_holdoff = 0
        self._scan_backoff = 1
        # gray-failure bookkeeping: replicas currently slowed by an
        # explicit chaos `slow` (rid -> factor) or by a degraded ICI
        # domain — the ground truth false-positive accounting is
        # judged against
        self._slow_factor: Dict[int, float] = {}
        self._link_slow: set = set()
        self._probe_last: Dict[str, float] = {}
        self._probe_n: Dict[str, int] = {}
        self._migrate_pending: List[int] = []
        # overload containment (docs/OVERLOAD.md): client retries
        # and hedge timers are EventHeap lanes on the virtual clock
        # — never wall time — so the event core treats them as any
        # other timed source and replays stay byte-identical
        self._retry_heap = EventHeap()   # (due_s, ARRIVAL, request)
        self._hedge_heap = EventHeap()   # (due_s, COMPLETION, ...)
        self._attempts: Dict[str, int] = {}
        self._hedges: Dict[str, dict] = {}
        self._hedge_dropped: set = set()
        self._completed_ids: set = set()
        # silent data corruption (docs/SDC.md): the sampled
        # duplicate-compute audit lane plus integrity detections.
        # All of it inert (and byte-invisible) at audit_frac 0 with
        # no sdc chaos in the plan.
        self._audit_frac = resolve_audit_frac(cfg.audit_frac)
        if self._audit_frac > 0.0 and self._disagg is not None:
            raise ValueError(
                "FleetConfig.audit_frac does not compose with "
                "disagg phase pools: audit copies are whole-request "
                "re-executions on unified replicas")
        self._audit_heap = EventHeap()
        self._audits: Dict[str, dict] = {}
        # replica id -> virtual time its corruption was DETECTED
        # (the no-corruption-escapes-after-detection anchor)
        self._sdc_detect_s: Dict[int, float] = {}
        self._sdc_active = self._audit_frac > 0.0
        # training tenancy (docs/TRAINING.md): gangs co-scheduled
        # under serving on the same inventory, strict priority
        self.trainer: Optional[TrainingTenant] = None
        if cfg.sched is not None:
            self._init_scheduler(cfg.sched)
        if cfg.training is not None:
            if self.sched is None:
                raise ValueError(
                    "FleetConfig.training needs a scheduler-backed "
                    "fleet (set FleetConfig.sched): training gangs "
                    "are scheduler-placed workloads")
            self.trainer = TrainingTenant(cfg.training, self.sched)

    # -- model zoo / per-generation pricing (docs/ZOO.md) -------------

    def _gen_of(self, rid: int) -> str:
        """The generation a replica id prices against — the declared
        cycle, so scale-up replicas join it deterministically."""
        return self._gen_cycle[rid % len(self._gen_cycle)]

    def _make_gen_replica(self, rid: int) -> SimReplica:
        """A replica priced off its generation's calibration; on zoo
        fleets it also carries the per-model pricing maps, warms its
        generation's placement, and reports swaps into the
        LANE_MODEL_SWAP ledger."""
        from kind_tpu_sim.fleet import zoo as zoo_mod

        gen = self._gen_of(rid)
        cal = self._gen_cals[gen]
        sim = self.cfg.sim
        if self._zoo is not None:
            rcfg = zoo_mod.model_sim_config(
                self._zoo, cal,
                max_slots=sim.max_slots,
                max_queue=sim.max_queue,
                prefix_cache_entries=sim.prefix_cache_entries,
                resident_model=self._gen_residents[gen])
        else:
            rcfg = calibrated_sim_config(
                cal,
                max_slots=sim.max_slots,
                max_queue=sim.max_queue,
                prefix_cache_entries=sim.prefix_cache_entries)
        replica = SimReplica(rid, rcfg)
        if self._zoo is not None:
            replica.on_swap = self._on_swap
        return replica

    def _on_swap(self, ev) -> None:
        """A replica started loading new model weights: the latency
        already rides the admitted slot's closed-form timeline, so
        the LANE_MODEL_SWAP event is pure bookkeeping — drained into
        the swap ledger in deterministic (ready, lane, seq) order."""
        self._swap_heap.push(ev.ready_s, LANE_MODEL_SWAP, ev)
        metrics.zoo_board().incr("model_swaps")

    # -- scheduler-backed placement (docs/SCHED.md) -------------------

    def _init_scheduler(self, sc: "FleetSchedConfig") -> None:
        """Replicas become gangs on a real inventory: the initial
        fleet binds at t=0 (a fleet that cannot place its floor is a
        config error, not a simulation), scale-ups queue through the
        scheduler, and node chaos evicts through the same
        ``replica_preempt`` displacement machinery."""
        from kind_tpu_sim import sched as sched_mod

        self.sched = sched_mod.ClusterScheduler(
            sched_mod.build_inventory(list(sc.pods), zone=sc.zone,
                                      rack_pods=sc.rack_pods),
            sched_mod.SchedConfig(policy=sc.policy,
                                  bind_s=sc.bind_s),
            on_evict=self._on_gang_evict)
        self._sched_cfg = sc
        self._gang_replica: Dict[str, int] = {}
        # gangs whose bind we are waiting on: name -> requested_at
        self._gang_requested: Dict[str, float] = {}
        self.time_to_routable: List[float] = []
        for replica in self.replicas:
            name = f"replica-{replica.replica_id}"
            self.sched.submit(self._gang_request(name), 0.0)
            self._gang_replica[name] = replica.replica_id
        bound = self.sched.step(0.0)
        if len(bound) < len(self.replicas):
            raise ValueError(
                f"inventory cannot place the initial "
                f"{len(self.replicas)} replica(s); "
                f"{len(bound)} bound")

    def _gang_request(self, name: str):
        from kind_tpu_sim import sched as sched_mod

        sc = self._sched_cfg
        return sched_mod.SliceRequest(
            name=name, accelerator=sc.replica_accelerator,
            topology=sc.replica_topology, priority=sc.priority)

    def _replica_by_id(self, rid: int):
        for r in self.replicas + self._draining:
            if r.replica_id == rid:
                return r
        return None

    def _on_gang_evict(self, request) -> None:
        """Scheduler eviction -> fleet displacement: the victim
        replica preempts through the existing chaos machinery (its
        load requeues at the router FRONT) and the gang rejoins the
        pending queue; the replica heals only after rebind+warmup."""
        if (self.trainer is not None
                and self.trainer.owns(request.name)):
            bound = self.sched.bound.get(request.name)
            if bound is not None:
                # defrag moved the gang (it is ALREADY rebound): a
                # checkpointed repartition at the same shape
                dom = self.sched.inv.domains[bound.placement.domain]
                self.trainer.on_migrated(
                    request.name, self._now, dom.link_factor,
                    self._sched_cfg.bind_s)
            else:
                self.trainer.on_evicted(request.name, self._now)
            return
        rid = self._gang_replica.get(request.name)
        if rid is None:
            return
        victim = self._replica_by_id(rid)
        now = self._now
        if victim is not None and victim.healthy:
            displaced = victim.fail(now)
            self._requeue_front(displaced)
            self.preemptions += 1
            metrics.fleet_board().incr("replica_preemptions")
            metrics.recovery_log().record(
                "fleet_gang_evict", gang=request.name,
                displaced=len(displaced), at_s=round(now, 6))
        self._gang_requested[request.name] = now

    def _sched_step(self, now: float) -> None:
        """Advance the scheduler: bind pending gangs; a bound gang's
        replica becomes routable after bind latency + warm-up (the
        measured queue-wait + placement + warm-up path that replaces
        the flat constant)."""
        if not self.sched.pending:
            return
        warmup = (self.autoscaler.warmup_s
                  if self.autoscaler is not None
                  else resolve_warmup_s())
        for gang in self.sched.step(now):
            name = gang.request.name
            if (self.trainer is not None
                    and self.trainer.owns(name)):
                dom = self.sched.inv.domains[gang.placement.domain]
                self.trainer.on_bound(name, now, dom.link_factor,
                                      self._sched_cfg.bind_s)
                continue
            requested = self._gang_requested.pop(name, now)
            # warm-up is collective-heavy (compile + init all-reduce
            # smokes), so a degraded-link domain inflates it by the
            # same modeled share as steady-state service
            dom = self.sched.inv.domains[gang.placement.domain]
            warm_mult = collectives.ici_slowdown(
                dom.link_factor, self._sched_cfg.ici_fraction)
            ready_at = (now + self._sched_cfg.bind_s
                        + warmup * warm_mult)
            ttr = round(ready_at - requested, 6)
            self.time_to_routable.append(ttr)
            rid = self._gang_replica[name]
            existing = self._replica_by_id(rid)
            if existing is not None:
                # evicted replica rebound: heals at ready_at
                self._rebinding.push(ready_at, LANE_CHAOS, existing)
            else:
                # autoscaler scale-up: new replica warms up
                self._warming.push(
                    ready_at, LANE_AUTOSCALER,
                    (self.factory(rid),
                     f"bound+warm (time_to_routable={ttr}s)"))

    def _apply_node_chaos(self, ev: "ChaosEvent",
                          now: float) -> None:
        from kind_tpu_sim import sched as sched_mod

        names = sorted(self.sched.inv.nodes)
        node = names[ev.target % len(names)]
        sched_mod.apply_node_event(self.sched, ev.action, node, now)
        if ev.action in ("node_drain", "node_fail"):
            metrics.recovery_log().record(
                f"fleet_{ev.action}", node=node,
                at_s=round(now, 6))

    def _apply_domain_chaos(self, ev: "ChaosEvent",
                            now: float) -> None:
        """Correlated failure (docs/SDC.md): one draw fails EVERY
        node sharing a rack/power failure domain — the point of
        modeling correlation is that this is strictly worse than the
        same chip count failing independently."""
        from kind_tpu_sim import sched as sched_mod

        fds = self.sched.inv.failure_domains()
        if not fds:
            raise ValueError(
                "domain chaos needs correlated failure domains "
                "(set FleetSchedConfig.rack_pods)")
        fd = fds[ev.target % len(fds)]
        action = ("node_fail" if ev.action == "domain_fault"
                  else "node_restore")
        nodes = self.sched.inv.failure_domain_nodes(fd)
        for node in nodes:
            sched_mod.apply_node_event(self.sched, action, node,
                                       now)
        self._sdc_active = True
        metrics.integrity_board().incr(
            "domain_faults" if action == "node_fail"
            else "domain_restores")
        metrics.recovery_log().record(
            f"fleet_{ev.action}", failure_domain=fd,
            nodes=len(nodes), at_s=round(now, 6))

    # -- gray failures (docs/HEALTH.md) -------------------------------

    def _apply_link_chaos(self, ev: "ChaosEvent",
                          now: float) -> None:
        from kind_tpu_sim import sched as sched_mod

        domains = sorted(self.sched.inv.domains)
        domain = domains[ev.target % len(domains)]
        if ev.action == "link_degrade":
            sched_mod.apply_link_event(
                self.sched, "link_degrade", domain,
                max(1e-3, ev.param), now)
            metrics.recovery_log().record(
                "fleet_link_degrade", domain=domain,
                factor=ev.param, at_s=round(now, 6))
        else:
            sched_mod.apply_link_event(
                self.sched, "link_restore", domain, 1.0, now)
            # the fault is gone: lift the avoid marks quarantine-
            # driven migrations left on the domain's nodes
            for node in self.sched.inv.domains[domain].nodes.values():
                self.sched.inv.mark_avoid(node.name, False)
        self._refresh_link_slowdowns(now)

    def _refresh_link_slowdowns(self, now: float) -> None:
        """Recompute every placed replica's service-time inflation
        from its ICI domain's link state (plus any explicit `slow`
        chaos), and the ground-truth set of link-slowed replicas."""
        self._link_slow = set()
        sc = self._sched_cfg
        for name, gang in sorted(self.sched.bound.items()):
            rid = self._gang_replica.get(name)
            if rid is None:
                if (self.trainer is not None
                        and self.trainer.owns(name)):
                    # link state changed under a training gang: its
                    # ring slows/heals — a pure rate change, no
                    # checkpoint (docs/TRAINING.md)
                    self.trainer.gangs[name].reprice(
                        now,
                        self.sched.inv.domains[
                            gang.placement.domain].link_factor)
                continue
            replica = self._replica_by_id(rid)
            if replica is None or not hasattr(replica,
                                              "set_slowdown"):
                continue
            mult = collectives.ici_slowdown(
                self.sched.inv.domains[gang.placement.domain]
                .link_factor, sc.ici_fraction)
            if mult > 1.0:
                self._link_slow.add(rid)
            replica.set_slowdown(
                max(mult, self._slow_factor.get(rid, 1.0)))

    def _gray_truth(self) -> set:
        return set(self._slow_factor) | self._link_slow

    def _on_health_transition(self, rid: int, transition: str,
                              now: float) -> None:
        if transition != "quarantined":
            return
        metrics.recovery_log().record(
            "fleet_replica_quarantine", replica=rid,
            at_s=round(now, 6))
        if rid not in self._gray_truth():
            # detection fired on a replica nothing is degrading —
            # the no-churn acceptance bound counts these
            metrics.health_board().incr("false_positives")
        if self.sched is not None:
            self._migrate_pending.append(rid)

    def _drain_migrations(self, now: float) -> None:
        """At most ONE gray migration in flight at a time (the
        maxUnavailable=1 discipline): evicting every quarantined
        gang at once would trade a gray slowdown for a total outage.
        A quarantined replica waiting its turn keeps serving its
        in-flight work (slowly) and remains the router's
        last-resort fallback — graceful degradation, not a cliff."""
        if not self._migrate_pending:
            return
        if self._rebinding or self._gang_requested:
            return  # a migration/rebind is already in flight
        rid = self._migrate_pending.pop(0)
        if (self.health is not None
                and not self.health.quarantined(f"replica-{rid}")):
            return  # restored in the meantime; nothing to move
        self._migrate_gang(rid, now)

    def _migrate_gang(self, rid: int, now: float) -> None:
        """Quarantined replica on a scheduler-backed fleet: migrate
        its gang off the suspect hardware — evict (displaced load
        requeues at the router FRONT via the existing preemption
        machinery), mark the vacated nodes avoid, and let the next
        scheduling pass rebind it; degraded-domain scoring plus the
        avoid marks steer it onto healthy hardware."""
        name = f"replica-{rid}"
        gang = self.sched.bound.get(name)
        if gang is None:
            return
        for node in gang.placement.node_names:
            self.sched.inv.mark_avoid(node, True)
        self.sched.evict_gang(
            name, now,
            reason="gray: replica quarantined by the failure "
                   "detector; migrating off suspect hardware")
        metrics.health_board().incr("gray_migrations")

    def _probe_quarantined(self, now: float) -> None:
        """Inject one SYNTHETIC probe request per suspect-or-
        quarantined (but alive) replica per probe interval. Probing
        SUSPECTS matters as much as probing quarantined replicas:
        the latency-aware router starves a suspect of user traffic,
        which would otherwise starve the detector of the very
        samples it needs to confirm or clear the suspicion. Probes
        never enter the SLO log — user traffic is not sacrificed to
        find out whether the hardware recovered."""
        from kind_tpu_sim.fleet.loadgen import TraceRequest

        for replica in self.replicas:
            comp = f"replica-{replica.replica_id}"
            if (not replica.healthy
                    or self.health.state(comp) == "healthy"):
                continue
            last = self._probe_last.get(comp)
            if (last is not None and
                    now - last < self.health.cfg.probe_interval_s):
                continue
            self._probe_last[comp] = now
            n = self._probe_n.get(comp, 0)
            self._probe_n[comp] = n + 1
            probe = TraceRequest(
                request_id=f"__probe-{replica.replica_id}-{n}",
                arrival_s=round(now, 6), prompt=(1,) * 8,
                max_new=4, seed=0)
            if replica.submit(probe, now):
                metrics.health_board().incr("probe_dispatches")

    def _observe_health(self, rid: int, comp: ReplicaCompletion,
                        now: float) -> None:
        # the detector's one channel is TPOT (decode time per post-
        # first token): a pure service-time signal, uncontaminated
        # by queueing or prompt-length spread — exactly what a gray
        # slowdown inflates and a healthy replica holds constant
        if comp.tokens < 2 or comp.first_s is None:
            return
        sample = ((comp.finish_s - comp.first_s)
                  / (comp.tokens - 1))
        transition = self.health.observe(
            f"replica-{rid}", sample, now=now)
        if transition is not None:
            self._on_health_transition(rid, transition, now)

    # -- multi-tenancy (docs/TENANCY.md) ------------------------------

    def _install_tenant_caps(self, replica) -> None:
        """Give an analytic replica its per-tenant prefix-cache caps
        (the KV budget applied to the cache stand-in). A no-op
        without isolation, on engine replicas, or when no tenant
        declares a budget fraction below 1."""
        ten = self.tenancy
        if ten is None or not ten.isolation:
            return
        rcfg = getattr(replica, "cfg", None)
        if rcfg is None or not hasattr(rcfg, "prefix_cache_entries"):
            return
        entries = rcfg.prefix_cache_entries
        if entries <= 0:
            return
        caps: Dict[str, int] = {}
        for t in ten.cfg.tenants:
            cap = ten.kv_budget(t.name, entries)
            if cap is not None:
                caps[t.name] = cap
        if caps:
            replica.tenant_prefix_caps = caps

    def _tenant_key(self, req) -> str:
        """The overload layer's tenant dimension: the request's
        tenant under isolation, '' otherwise — so untenanted runs
        keep the PR 9 per-origin bucket stream untouched."""
        ten = self.tenancy
        if ten is None or not ten.isolation:
            return ""
        return tenant_of(req)

    # -- overload containment (docs/OVERLOAD.md) ----------------------

    def _offer_arrival(self, req: TraceRequest, now: float,
                       fresh: bool) -> None:
        """One client-side admission: fresh arrivals earn retry
        budget, the brownout ladder sheds low tiers and caps
        ``max_new`` at its admission gate, and the router takes what
        survives (its own shed path handles a full central queue)."""
        ten = self.tenancy
        if ten is not None and fresh:
            # tenant quota admission happens BEFORE the retry-budget
            # earn: a quota-refused request never entered the system,
            # so it must not fund anyone's retries. Quota sheds are
            # deliberate policy, not breach — they stay out of the
            # brownout window.
            if ten.admit(req, now) is not None:
                metrics.tenant_board().incr("tenant_quota_shed")
                self._record(ReplicaCompletion(
                    request=req, dispatch_s=now, first_s=None,
                    finish_s=now, tokens=0, tokens_crc=0,
                    finish_reason="shed"), -1,
                    brownout_observe=False)
                return
        ov = self.overload
        if ov is not None:
            if fresh:
                ov.earn_retry("local", self._tenant_key(req))
            bo = ov.brownout
            if ten is not None and ten.isolation:
                # brownout sheds by DECLARED tier when tenancy is
                # on: the batch scavenger browns out first, never a
                # pseudo-random id-hash slice of everyone
                tier = ten.tier(tenant_of(req))
            else:
                tier = request_tier(req.request_id,
                                    ov.cfg.low_tier_frac)
            if bo.sheds_tier(tier):
                metrics.fleet_board().incr("brownout_shed")
                self._record(ReplicaCompletion(
                    request=req, dispatch_s=now, first_s=None,
                    finish_s=now, tokens=0, tokens_crc=0,
                    finish_reason="shed"), -1,
                    brownout_observe=False)
                return
            capped = bo.cap_max_new(req.max_new)
            if capped != req.max_new:
                req = dataclasses.replace(req, max_new=capped)
        shed = self.router.offer(req, now)
        if shed is not None:
            self._record(shed, -1)

    def _on_place(self, req: TraceRequest, replica,
                  now: float) -> None:
        """Router placement hook: arm the hedge timer. The delay is
        the p9x of observed dispatch->finish service times — a
        deterministic pure function of completions seen — so the
        hedge fires only once the primary is provably a tail case."""
        ov = self.overload
        rid = req.request_id
        if rid.startswith("__probe-"):
            return
        if not ov.hedge_enabled() or rid in self._hedges:
            return
        self._hedge_heap.push(now + ov.hedge_delay_s(),
                              LANE_COMPLETION, (req, replica))

    def _fire_hedges(self, now: float) -> None:
        """Due hedge timers: a request still in flight past its
        hedge delay gets a copy on the second-best candidate —
        budget-gated, so hedging shuts itself off under saturation
        instead of doubling the overload."""
        ov = self.overload
        for req, primary in self._hedge_heap.pop_due(now):
            rid = req.request_id
            if rid in self._completed_ids or rid in self._hedges:
                continue
            if not ov.hedge_enabled():
                continue
            if not ov.spend_hedge(self._tenant_key(req)):
                continue
            for cand in self.router._pick_order(req, now):
                if cand is primary:
                    continue
                if cand.submit(req, now):
                    self._hedges[rid] = {"primary": primary,
                                         "hedge": cand}
                    ov.incr("hedges_issued")
                    ov.breaker_dispatch(
                        f"replica-{cand.replica_id}")
                    break

    def _handle_completion(self, replica, comp: ReplicaCompletion,
                           now: float) -> None:
        """One replica completion through the overload filters:
        late completions of cancelled hedge losers are dropped, the
        first completion of a hedged pair wins and cancels the
        loser mid-stream, duplicates (displacement races) dedupe on
        the id."""
        ov = self.overload
        if ov is None:
            self._record(comp, replica.replica_id)
            return
        rid = comp.request.request_id
        if rid in self._hedge_dropped:
            self._hedge_dropped.discard(rid)
            ov.incr("hedge_late_drops")
            return
        if rid in self._completed_ids:
            return
        pair = self._hedges.pop(rid, None)
        if pair is not None:
            loser = (pair["hedge"] if replica is pair["primary"]
                     else pair["primary"])
            if replica is pair["hedge"]:
                ov.incr("hedge_wins")
            if (hasattr(loser, "cancel")
                    and loser.cancel(rid)):
                ov.incr("hedge_cancels")
            else:
                self._hedge_dropped.add(rid)
        self._record(comp, replica.replica_id)

    # -- disaggregated serving (docs/DISAGG.md) -----------------------

    def _on_prefill_done(self, replica, comp: ReplicaCompletion,
                         now: float) -> None:
        """A prefill-pool replica finished a prompt: price the KV
        transfer off the prompt length and ship it to the decode
        pool as a LANE_KV_TRANSFER event. Hedge duplicates dedupe
        here — one request ships exactly one KV cache."""
        rid = comp.request.request_id
        ov = self.overload
        if ov is not None and rid in self._hedge_dropped:
            self._hedge_dropped.discard(rid)
            ov.incr("hedge_late_drops")
            return
        if rid in self._prefill_done_ids or rid in self._completed_ids:
            return
        self._prefill_done_ids.add(rid)
        if ov is not None:
            pair = self._hedges.pop(rid, None)
            if pair is not None:
                loser = (pair["hedge"] if replica is pair["primary"]
                         else pair["primary"])
                if replica is pair["hedge"]:
                    ov.incr("hedge_wins")
                if (hasattr(loser, "cancel")
                        and loser.cancel(rid)):
                    ov.incr("hedge_cancels")
                else:
                    self._hedge_dropped.add(rid)
        if (not rid.startswith("__probe-")
                and self.cfg.slo.ttft_s is not None
                and comp.first_s is not None):
            # the prefill pool's scaling signal: TTFT attainment
            self._recent_ttft.append(
                comp.first_s - comp.request.arrival_s
                <= self.cfg.slo.ttft_s)
        kv_bytes = len(comp.request.prompt) * self._kv_per_tok
        transfer = kv_transfer_s(kv_bytes, self._disagg.tier,
                                 self._kv_factor)
        handoff = KvHandoff(
            request=comp.request, dispatch_s=comp.dispatch_s,
            first_s=comp.first_s, tokens=comp.tokens,
            kv_bytes=kv_bytes, from_replica=replica.replica_id)
        self._kv_heap.push(round(comp.finish_s + transfer, 9),
                           LANE_KV_TRANSFER, handoff)
        self._kv_handoffs += 1
        self._kv_bytes_total += kv_bytes
        self._kv_transfer_s_total += transfer
        metrics.disagg_board().incr("prefills_done")

    def _apply_disagg_chaos(self, ev: "ChaosEvent",
                            now: float) -> None:
        if ev.action == "prefill_pool_loss":
            displaced: List[TraceRequest] = []
            lost = 0
            for r in self.replicas:
                if (getattr(r, "phase", "unified") == "prefill"
                        and r.healthy):
                    displaced.extend(r.fail(now))
                    lost += 1
            self._requeue_front(displaced)
            self.preemptions += lost
            metrics.disagg_board().incr("prefill_pool_losses")
            metrics.recovery_log().record(
                "fleet_prefill_pool_loss", replicas=lost,
                displaced=len(displaced), at_s=round(now, 6))
        elif ev.action == "prefill_pool_restore":
            healed = 0
            for r in self.replicas:
                if (getattr(r, "phase", "unified") == "prefill"
                        and not r.healthy):
                    r.restore(now)
                    healed += 1
            metrics.recovery_log().record(
                "fleet_prefill_pool_restore", replicas=healed,
                at_s=round(now, 6))
        elif ev.action == "kv_degrade":
            # future transfers only: an in-flight transfer keeps its
            # scheduled delivery (the fault hits the link, not the
            # bytes already on the wire)
            self._kv_factor = max(1e-3, ev.param)
            metrics.disagg_board().incr("kv_degrades")
            metrics.recovery_log().record(
                "fleet_kv_degrade", factor=ev.param,
                at_s=round(now, 6))
        elif ev.action == "kv_restore":
            self._kv_factor = 1.0
            metrics.recovery_log().record(
                "fleet_kv_restore", at_s=round(now, 6))

    def _pool_members(self, phase: str) -> List:
        return [r for r in self.router.replicas
                if getattr(r, "phase", "unified") == phase]

    def _autoscale_pools(self, now: float) -> None:
        """One evaluation per pool per cadence: prefill scales on
        TTFT attainment + arrival backlog, decode on ITL attainment
        (when the policy sets ``itl_s``; queue-depth otherwise) +
        KV-lane backlog. Scale-down drains the pool's highest-id
        healthy replica, never below the declared floor."""
        changed = False
        for replica, reason in self._warming.pop_due(now):
            self.replicas.append(replica)
            self.router.replicas.append(replica)
            self._install_tenant_caps(replica)
            changed = True
            phase = getattr(replica, "phase", "unified")
            self._pool_scalers[phase].note_ready(
                now, len(self._pool_members(phase)), reason=reason)
        for phase in ("prefill", "decode"):
            scaler = self._pool_scalers[phase]
            members = self._pool_members(phase)
            routable = sum(
                1 for r in members
                if r.healthy and (self.health is None
                                  or not self.health.quarantined(
                                      f"replica-{r.replica_id}")))
            healthy_out = sum(r.outstanding() for r in members
                              if r.healthy)
            if phase == "prefill":
                backlog = len(self.router.queue) + healthy_out
                recent = list(self._recent_ttft)
            else:
                backlog = (len(self.router.kv_queue)
                           + len(self._kv_heap) + healthy_out)
                recent = list(self._recent_itl)
            attainment = (sum(recent) / len(recent)
                          if recent else None)
            action = scaler.evaluate(
                now, routable=routable, backlog=backlog,
                attainment=attainment)
            if action == "scale_up":
                rid = self._next_replica_id
                self._next_replica_id += 1
                self._warming.push(
                    now + scaler.warmup_s, LANE_AUTOSCALER,
                    (SimReplica(rid, self._disagg_sim_cfg,
                                phase=phase),
                     f"{phase} warmup complete"))
                metrics.disagg_board().incr(
                    f"{phase}_scale_ups")
            elif action == "scale_down":
                victims = [r for r in members if r.healthy]
                if not victims:
                    continue
                victim = max(victims, key=lambda r: r.replica_id)
                self.router.replicas.remove(victim)
                self.replicas.remove(victim)
                self._draining.append(victim)
                changed = True
                metrics.disagg_board().incr(
                    f"{phase}_scale_downs")
        if changed and self._cols is not None:
            self._cols.rebuild(self.replicas)

    def displace_disagg(self) -> List[TraceRequest]:
        """Drain the whole KV lane — queued handoffs AND in-flight
        transfers — back to base requests (each re-prefills from
        scratch). The cell-loss displacement path (globe/cell.py)
        calls this so a failed disagg cell loses zero work."""
        out: List[TraceRequest] = []
        for h in self._kv_heap.pop_due(float("inf")):
            rid = h.request.request_id
            if rid in self._kv_cancelled:
                # lazily-cancelled transfer: the hedge winner
                # already owns this request — do not resurrect it
                self._kv_cancelled.discard(rid)
                continue
            out.append(h.request)
        out.extend(h.request for h in self.router.kv_queue)
        self.router.kv_queue = []
        for r in out:
            self._prefill_done_ids.discard(r.request_id)
        return out

    def _requeue_front(self, displaced: List) -> None:
        """The displacement funnel: a request heading back to the
        arrival queue must be allowed to prefill AGAIN, so drop its
        id from the prefill dedupe set (which exists to absorb
        hedge duplicates, not legitimate re-prefills — without this
        a request displaced mid-decode would re-prefill, hit the
        dedupe, and vanish)."""
        if self._audits:
            # a displaced audit copy dies with its replica (it is
            # synthetic — never user work to preserve): the audit
            # concludes on the results it already has
            kept = []
            for req in displaced:
                rid = getattr(req, "request_id", "")
                if "~a" in rid:
                    self._conclude_audit(rid.split("~a", 1)[0],
                                         self._now)
                    continue
                kept.append(req)
            displaced = kept
        if self._disagg is not None:
            for req in displaced:
                base = (req.request
                        if getattr(req, "is_kv_handoff", False)
                        else req)
                self._prefill_done_ids.discard(base.request_id)
        self.router.requeue_front(displaced)

    def _maybe_retry(self, comp: ReplicaCompletion,
                     now: float) -> None:
        """The client retry model: a shed or deadline-expired
        attempt is retried after deterministic doubling backoff IF
        the origin's token-bucket budget allows — a saturated fleet
        sees retry load shrink, not amplify, and the suppressed
        count proves it."""
        ov = self.overload
        if ov is None or comp.finish_reason not in (
                "shed", "deadline_exceeded"):
            return
        if ov.cfg.max_attempts <= 1:
            return  # client retries disabled at this tier
        req = comp.request
        base = req.request_id.split("~r", 1)[0]
        attempt = self._attempts.get(base, 1)
        if attempt >= ov.cfg.max_attempts:
            ov.incr("retries_exhausted")
            return
        if not ov.spend_retry("local", self._tenant_key(req)):
            return
        self._attempts[base] = attempt + 1
        delay = ov.cfg.retry_backoff_s * (2 ** (attempt - 1))
        at = round(now + delay, 6)
        self._retry_heap.push(at, LANE_ARRIVAL, dataclasses.replace(
            req, request_id=f"{base}~r{attempt}", arrival_s=at))

    # -- silent data corruption (docs/SDC.md) -------------------------

    def _dispatch_audit(self, base_id: str, now: float) -> None:
        """A due audit: re-execute the request on a replica that
        produced NONE of its existing results. Copies are submitted
        directly (the health-probe precedent) — real slot occupancy,
        never SLO traffic. With no eligible second replica the audit
        is inconclusive and the original answer stands."""
        st = self._audits.get(base_id)
        if st is None:
            return
        req = st["req"]
        model = getattr(req, "model", "")
        target = None
        for r in self.replicas:
            if not r.healthy or r.replica_id in st["results"]:
                continue
            if (self.health is not None
                    and self.health.quarantined(
                        f"replica-{r.replica_id}")):
                continue
            can = getattr(r, "can_serve", None)
            if can is not None and not can(model):
                continue
            target = r
            break
        st["copies"] += 1
        copy = dataclasses.replace(
            req,
            request_id=f"{base_id}~a{st['copies']}",
            arrival_s=round(now, 6),
            deadline_s=None)
        if target is None or not target.submit(copy, now):
            self._conclude_audit(base_id, now)
            return
        metrics.integrity_board().incr("audit_copies")

    def _on_audit_result(self, replica,
                         comp: ReplicaCompletion,
                         now: float) -> None:
        """An audit copy finished: compare fingerprints. Agreement
        closes the audit; the first disagreement escalates to
        majority-of-three (one more copy on a third replica)."""
        base_id = comp.request.request_id.split("~a", 1)[0]
        st = self._audits.get(base_id)
        if st is None:
            return
        if comp.finish_reason != "length":
            # the copy died (deadline, displacement): inconclusive
            self._conclude_audit(base_id, now)
            return
        st["results"][replica.replica_id] = comp.tokens_crc
        st["order"].append(replica.replica_id)
        if (len(set(st["results"].values())) == 1
                or len(st["order"]) >= 3):
            self._conclude_audit(base_id, now)
            return
        # two answers disagree: somebody is corrupting — a third
        # copy disambiguates (replica-keyed corruption means two
        # defective chips can never agree in error)
        self._audit_heap.push(comp.finish_s,
                              LANE_INTEGRITY_AUDIT, base_id)

    def _conclude_audit(self, base_id: str, now: float) -> None:
        """Close one audit: majority names the culprit(s), ties
        break deterministically, and the ground-truth counters
        record whether a corrupted answer was caught before serve
        or escaped."""
        st = self._audits.pop(base_id, None)
        if st is None:
            return
        results = st["results"]
        order = st["order"]
        caught = False
        counts: Dict[int, int] = {}
        for c in results.values():
            counts[c] = counts.get(c, 0) + 1
        if len(order) >= 2 and max(counts.values()) < len(order):
            metrics.integrity_board().incr("audit_mismatches")
            if len(order) >= 3 and max(counts.values()) >= 2:
                good = next(c for c in counts
                            if counts[c] >= 2)
                culprits = [rid for rid in order
                            if results[rid] != good]
            elif len(order) >= 3:
                # three-way disagreement: at least two defective
                # chips — both ORIGINAL suspects are pulled and the
                # freshest answer is served
                culprits = order[:2]
            else:
                # no third replica was available: deterministic
                # tie-break — the original producer is the suspect
                # (conservative; a false positive here is charged
                # to the audit lane, not hidden)
                culprits = order[:1]
            for rid in culprits:
                self._sdc_quarantine(rid, now, cause="audit")
            caught = order[0] in culprits
        if st["corrupted"]:
            if caught:
                # the corrupted answer was withheld and replaced by
                # the verified copy before reaching the user
                st["entry"]["sdc_caught"] = True
                metrics.integrity_board().incr("corrupted_caught")
            else:
                metrics.integrity_board().incr("corrupted_served")

    def _sdc_quarantine(self, rid: int, now: float,
                        cause: str) -> None:
        """Integrity containment: the named replica's chip is
        defective — pull it NOW. The replica fails (displaced work
        requeues onto clean hardware; post-detection it can produce
        nothing further), the detector gets the STICKY integrity
        quarantine, and on a scheduler-backed fleet the defective
        chip leaves the node's allocatable capacity before the gang
        rebinds elsewhere."""
        if rid in self._sdc_detect_s:
            return
        self._sdc_detect_s[rid] = round(now, 6)
        self._sdc_active = True
        metrics.integrity_board().incr("chips_quarantined")
        metrics.recovery_log().record(
            "fleet_sdc_quarantine", replica=rid, cause=cause,
            at_s=round(now, 6))
        if self.health is not None:
            self.health.record_integrity(f"replica-{rid}", now,
                                         cause=cause)
        name = f"replica-{rid}"
        if (self.sched is not None
                and self.sched.bound.get(name) is not None):
            gang = self.sched.bound[name]
            # chip-granular, not whole-node: ONE chip leaves the
            # anchor node's capacity; the rest of the host serves on
            self.sched.inv.quarantine_chips(
                gang.placement.node_names[0], 1)
            self.sched.evict_gang(
                name, now,
                reason="sdc: integrity quarantine; rebinding off "
                       "the defective chip")
            return
        victim = self._replica_by_id(rid)
        if victim is not None and victim.healthy:
            displaced = victim.fail(now)
            self._requeue_front(displaced)
            self.preemptions += 1
            metrics.recovery_log().record(
                "fleet_sdc_chip_pulled", replica=rid,
                displaced=len(displaced), at_s=round(now, 6))

    def _on_train_sdc(self, verdict: dict, now: float) -> None:
        """A training gang's bisection named its culprit chip: hand
        it to quarantine — sticky integrity quarantine on the chip
        component, chip-granular capacity removal on its node."""
        gang = verdict["gang"]
        chip = verdict["chip"]
        self._sdc_active = True
        metrics.integrity_board().incr("chips_quarantined")
        metrics.recovery_log().record(
            "fleet_sdc_train_quarantine", gang=gang, chip=chip,
            at_s=round(now, 6))
        if self.health is not None:
            self.health.record_integrity(f"{gang}-chip-{chip}", now,
                                         cause="bisection")
        bound = self.sched.bound.get(gang) if self.sched else None
        if bound is not None:
            names = bound.placement.node_names
            per = max(1, bound.placement.chips_per_node)
            node = names[min(chip // per, len(names) - 1)]
            self.sched.inv.quarantine_chips(node, 1)

    # -- bookkeeping ---------------------------------------------------

    def _record(self, comp: ReplicaCompletion,
                replica_id: int,
                brownout_observe: bool = True) -> None:
        req = comp.request
        ok = self.tracker.observe(
            arrival_s=req.arrival_s, first_s=comp.first_s,
            finish_s=comp.finish_s, tokens=comp.tokens,
            shed=comp.finish_reason == "shed",
            deadline_exceeded=comp.finish_reason
            == "deadline_exceeded")
        self._recent.append(ok)
        entry = {
            "request_id": req.request_id,
            "replica": replica_id,
            "prefix_group": req.prefix_group,
            "arrival_s": round(req.arrival_s, 6),
            "dispatch_s": round(comp.dispatch_s, 6),
            "first_s": (round(comp.first_s, 6)
                        if comp.first_s is not None else None),
            "finish_s": round(comp.finish_s, 6),
            "tokens": comp.tokens,
            "tokens_crc": comp.tokens_crc,
            "finish_reason": comp.finish_reason,
            "slo_ok": ok,
        }
        if getattr(req, "tenant", ""):
            # conditional, like the TraceRequest wire format: every
            # untenanted completion log stays byte-identical
            entry["tenant"] = req.tenant
        if getattr(req, "model", ""):
            # same contract: unzooed completion logs stay
            # byte-identical
            entry["model"] = req.model
        corrupted = getattr(comp, "corrupted", False)
        if corrupted:
            # ground truth (docs/SDC.md), conditional: pre-SDC logs
            # keep their bytes
            entry["corrupted"] = True
            metrics.integrity_board().incr("corrupted_produced")
        self.log.append(entry)
        if (self._audit_frac > 0.0 and replica_id >= 0
                and comp.finish_reason == "length"
                and req.request_id not in self._audits
                and zlib.crc32(
                    ("audit:%d" % zlib.crc32(
                        req.request_id.encode("utf-8"))).encode(
                        "utf-8")) / 2**32 < self._audit_frac):
                # nested crc, NOT crc32(f"audit:{id}"): crc32 is
                # affine in the id bits, so any single-pass draw
                # over same-length ids differs from the replica's
                # "sdc:{rid}:{id}" corruption draw by a CONSTANT
                # XOR — deterministically (anti-)correlated, and
                # corrupted work could dodge sampling forever. The
                # inner crc's decimal re-encoding breaks linearity.
            # sampled into the duplicate-compute audit lane: the
            # response is withheld until a second replica's
            # re-execution agrees (or the majority decides)
            self._audits[req.request_id] = {
                "req": req, "entry": entry,
                "corrupted": corrupted,
                "results": {replica_id: comp.tokens_crc},
                "order": [replica_id], "copies": 0}
            self._audit_heap.push(comp.finish_s,
                                  LANE_INTEGRITY_AUDIT,
                                  req.request_id)
            metrics.integrity_board().incr("audits")
        elif corrupted:
            # not sampled: the wrong answer reaches the user
            metrics.integrity_board().incr("corrupted_served")
        if self._zoo is not None and getattr(req, "model", ""):
            mtracker = self._model_trackers.get(req.model)
            if mtracker is None:
                mtracker = SloTracker(self.cfg.slo)
                self._model_trackers[req.model] = mtracker
            mtracker.observe(
                arrival_s=req.arrival_s, first_s=comp.first_s,
                finish_s=comp.finish_s, tokens=comp.tokens,
                shed=comp.finish_reason == "shed",
                deadline_exceeded=comp.finish_reason
                == "deadline_exceeded")
        if self.tenancy is not None:
            name = tenant_of(req)
            tracker = self._tenant_trackers.get(name)
            if tracker is None:
                tracker = SloTracker(self.cfg.slo)
                self._tenant_trackers[name] = tracker
            tracker.observe(
                arrival_s=req.arrival_s, first_s=comp.first_s,
                finish_s=comp.finish_s, tokens=comp.tokens,
                shed=comp.finish_reason == "shed",
                deadline_exceeded=comp.finish_reason
                == "deadline_exceeded")
        if (self.health is not None and replica_id >= 0
                and comp.finish_reason not in
                ("shed", "deadline_exceeded")):
            self._observe_health(replica_id, comp, self._now)
        if (self._disagg is not None
                and self.cfg.slo.itl_s is not None
                and comp.first_s is not None and comp.tokens >= 2):
            # the decode pool's scaling signal: ITL attainment
            itl = ((comp.finish_s - comp.first_s)
                   / (comp.tokens - 1))
            self._recent_itl.append(itl <= self.cfg.slo.itl_s)
        if self.overload is not None:
            self._completed_ids.add(req.request_id)
            if brownout_observe:
                # brownout-shed completions stay OUT of the window:
                # the ladder must not read its own deliberate
                # degradation as continued breach
                self.overload.brownout.observe(ok)
            if replica_id >= 0 and comp.finish_reason != "shed":
                # breaker outcome = the SLO verdict: latency breach
                # and deadline expiry both count against the window
                self.overload.breaker_record(
                    f"replica-{replica_id}", ok, self._now)
            if (comp.first_s is not None
                    and comp.finish_reason
                    not in ("shed", "deadline_exceeded")):
                self.overload.observe_service(
                    comp.finish_s - comp.dispatch_s,
                    self._tenant_key(req))
            self._maybe_retry(comp, self._now)
        if self.on_complete is not None:
            self.on_complete(self.log[-1], comp)

    def _backlog(self) -> int:
        if self._cols is not None:
            return (len(self.router.queue)
                    + self._cols.healthy_outstanding())
        return (len(self.router.queue)
                + sum(r.outstanding() for r in self.replicas
                      if r.healthy))

    def _apply_chaos(self, now: float) -> None:
        while self.chaos_events and self.chaos_events[0].at_s <= now:
            ev = self.chaos_events.pop(0)
            if ev.action in ("train_preempt", "train_kill"):
                if self.trainer is None:
                    raise ValueError(
                        f"{ev.action} chaos needs a training "
                        "tenancy (FleetConfig.training)")
                self.trainer.apply_chaos(ev.action, ev.target, now)
                continue
            if ev.action in ("prefill_pool_loss",
                             "prefill_pool_restore",
                             "kv_degrade", "kv_restore"):
                if self._disagg is None:
                    raise ValueError(
                        f"{ev.action} chaos needs a disaggregated "
                        "fleet (FleetConfig.disagg)")
                self._apply_disagg_chaos(ev, now)
                continue
            if ev.action == "model_swap_evict":
                # one storm pulse (docs/ZOO.md model_swap_storm):
                # every replica's resident model is dropped, so the
                # next model-stamped request it serves pays the full
                # weight-load — the swap lane's worst case
                if self._zoo is None:
                    raise ValueError(
                        f"{ev.action} chaos needs a model zoo "
                        "(FleetConfig.zoo)")
                evicted = 0
                for r in self.replicas:
                    if getattr(r, "resident_model", ""):
                        r.resident_model = ""
                        evicted += 1
                metrics.recovery_log().record(
                    "fleet_model_swap_evict", evicted=evicted,
                    at_s=round(now, 6))
                continue
            if ev.action == "sdc_train_chip":
                if self.trainer is None:
                    raise ValueError(
                        "sdc_train_chip chaos needs a training "
                        "tenancy (FleetConfig.training)")
                frac = (ev.param if ev.param > 0
                        else float(knobs.get(knobs.SDC_RATE)))
                self._sdc_active = True
                self.trainer.apply_sdc(ev.target, frac, now)
                continue
            if ev.action in ("domain_fault", "domain_restore"):
                if self.sched is None:
                    raise ValueError(
                        f"{ev.action} chaos needs a scheduler-"
                        "backed fleet (FleetConfig.sched)")
                self._apply_domain_chaos(ev, now)
                continue
            if ev.action.startswith("node_"):
                if self.sched is None:
                    raise ValueError(
                        f"{ev.action} chaos needs a scheduler-"
                        "backed fleet (FleetConfig.sched)")
                self._apply_node_chaos(ev, now)
                continue
            if ev.action.startswith("link_"):
                if self.sched is None:
                    raise ValueError(
                        f"{ev.action} chaos needs a scheduler-"
                        "backed fleet (FleetConfig.sched)")
                self._apply_link_chaos(ev, now)
                continue
            victim = next((r for r in self.replicas
                           if r.replica_id == ev.target), None)
            if victim is None:
                continue
            if ev.action == "slow":
                factor = max(1.0, ev.param)
                self._slow_factor[ev.target] = factor
                if hasattr(victim, "set_slowdown"):
                    victim.set_slowdown(factor)
                metrics.recovery_log().record(
                    "fleet_replica_slow", replica=ev.target,
                    factor=factor, at_s=round(now, 6))
            elif ev.action == "unslow":
                self._slow_factor.pop(ev.target, None)
                if hasattr(victim, "set_slowdown"):
                    victim.set_slowdown(1.0)
                if self.sched is not None:
                    # re-apply any remaining link-induced inflation
                    self._refresh_link_slowdowns(now)
                metrics.recovery_log().record(
                    "fleet_replica_unslow", replica=ev.target,
                    at_s=round(now, 6))
            elif ev.action == "sdc_chip":
                # the defective chip (docs/SDC.md): no heal event
                # exists — only integrity quarantine stops it
                frac = (ev.param if ev.param > 0
                        else float(knobs.get(knobs.SDC_RATE)))
                if hasattr(victim, "set_corrupt"):
                    victim.set_corrupt(frac)
                    self._sdc_active = True
                metrics.recovery_log().record(
                    "fleet_sdc_chip", replica=ev.target,
                    frac=round(frac, 6), at_s=round(now, 6))
            elif ev.action == "preempt" and victim.healthy:
                displaced = victim.fail(now)
                self._requeue_front(displaced)
                self.preemptions += 1
                metrics.fleet_board().incr("replica_preemptions")
                metrics.recovery_log().record(
                    "fleet_replica_preempt", replica=ev.target,
                    displaced=len(displaced),
                    at_s=round(now, 6))
            elif ev.action == "restore" and not victim.healthy:
                victim.restore(now)
                metrics.recovery_log().record(
                    "fleet_replica_restore", replica=ev.target,
                    at_s=round(now, 6))

    def _autoscale(self, now: float) -> None:
        scaler = self.autoscaler
        changed = False
        # warming replicas come online first
        for replica, reason in self._warming.pop_due(now):
            self.replicas.append(replica)
            self.router.replicas.append(replica)
            self._install_tenant_caps(replica)
            changed = True
            scaler.note_ready(now, len(self.router.replicas),
                              reason=reason)
        # quarantined capacity is MISSING capacity: the autoscaler
        # must not count a replica the router refuses to route to
        routable = sum(
            1 for r in self.router.replicas
            if r.healthy and (self.health is None
                              or not self.health.quarantined(
                                  f"replica-{r.replica_id}")))
        recent = list(self._recent)
        attainment = (sum(recent) / len(recent)
                      if recent else None)
        action = scaler.evaluate(
            now, routable=routable, backlog=self._backlog(),
            attainment=attainment)
        if action == "scale_up":
            rid = self._next_replica_id
            self._next_replica_id += 1
            if self.sched is not None:
                # routable only after queue wait + placement +
                # warm-up — the scheduler path (docs/SCHED.md)
                name = f"replica-{rid}"
                self.sched.submit(self._gang_request(name), now)
                self._gang_replica[name] = rid
                self._gang_requested[name] = now
            else:
                self._warming.push(
                    now + scaler.warmup_s, LANE_AUTOSCALER,
                    (self.factory(rid), "warmup complete"))
        elif action == "scale_down":
            # drain the highest-id healthy replica: no new traffic,
            # removed once idle — scale-down never displaces work
            victim = max((r for r in self.router.replicas
                          if r.healthy),
                         key=lambda r: r.replica_id)
            self.router.replicas.remove(victim)
            self.replicas.remove(victim)
            self._draining.append(victim)
            changed = True
        if changed and self._cols is not None:
            self._cols.rebuild(self.replicas)

    # -- the loop ------------------------------------------------------

    def step(self, now: float, tick: float,
             pending: Optional[deque] = None) -> None:
        """One fleet tick at virtual time ``now`` — the body of
        :meth:`run`'s loop, exposed so an outer driver (the globe
        layer's cells, docs/GLOBE.md) can advance N fleets in
        lockstep on one shared clock. ``pending`` is the
        arrival-ordered deque still to be offered (default: this
        sim's own trace); an external driver feeds its own deque and
        owns the clock."""
        if pending is None:
            pending = self._pending
        self._now = now
        self._apply_chaos(now)
        if self.sched is not None:
            if self.trainer is not None:
                # submit due gang arrivals, commit closed-form
                # progress, release finished gangs' inventory —
                # all BEFORE the scheduling pass sees the queue
                self.trainer.tick(now)
                for verdict in self.trainer.drain_sdc_verdicts():
                    self._on_train_sdc(verdict, now)
            self._drain_migrations(now)
            self._sched_step(now)
            healed = self._rebinding.pop_due(now)
            for replica in healed:
                replica.restore(now)
                if getattr(replica, "corrupt_frac", 0.0):
                    # the gang rebound onto replacement hardware —
                    # the defective chip stayed behind in quarantine
                    replica.set_corrupt(0.0)
                metrics.recovery_log().record(
                    "fleet_gang_rebound",
                    replica=replica.replica_id,
                    at_s=round(now, 6))
            if healed:
                self._refresh_link_slowdowns(now)
            for replica in healed:
                comp = f"replica-{replica.replica_id}"
                if (self.health is not None
                        and self.health.quarantined(comp)):
                    # the gang rebound onto healthy hardware —
                    # the replacement is a new individual
                    self.health.restore(comp, now,
                                        reason="rebound")
        while pending and pending[0].arrival_s <= now:
            self._offer_arrival(pending.popleft(), now, fresh=True)
        if self.overload is not None:
            for req in self._retry_heap.pop_due(now):
                self._offer_arrival(req, now, fresh=False)
        if self._disagg is not None:
            # KV transfers that finished by this boundary land in
            # the router's decode lane, dispatched this same pass
            for handoff in self._kv_heap.pop_due(now):
                if self._kv_cancelled:
                    rid = handoff.request.request_id
                    if rid in self._kv_cancelled:
                        self._kv_cancelled.discard(rid)
                        continue
                metrics.disagg_board().incr("kv_handoffs_delivered")
                self.router.offer_handoff(handoff)
        if self._zoo is not None:
            # finished weight loads land in the swap ledger: pure
            # bookkeeping — the load latency already rode the
            # admitted slot's closed-form timeline at admission —
            # drained in deterministic (ready, lane, seq) order
            for ev in self._swap_heap.pop_due(now):
                self._swap_log.append(ev.as_dict())
        if self._audits or self._audit_heap:
            # due integrity audits: dispatch the duplicate-compute
            # copy (or the round-3 tiebreaker) as REAL occupancy on
            # a second replica — docs/SDC.md "audit economics"
            for base_id in self._audit_heap.pop_due(now):
                self._dispatch_audit(base_id, now)
        if self.health is not None and (pending
                                        or self.router.queue):
            # probe only while user traffic still flows — an
            # endless probe loop must never keep a drained sim
            # alive
            self._probe_quarantined(now)
        for comp in self.router.dispatch(now):
            self._record(comp, -1)
        if self.overload is not None:
            self._fire_hedges(now)
        if self._cols is not None:
            # columnar fan-out: visit only replicas that can act in
            # this window, in the same ascending list order — the
            # skipped ones are provable no-ops (fleet/columnar.py)
            reps = self._cols.replicas
            targets = [reps[i] for i in
                       self._cols.active_indices(now + tick)]
        else:
            targets = list(self.replicas)
        for replica in targets:
            for comp in replica.tick(now, tick):
                if comp.request.request_id.startswith(
                        "__probe-"):
                    # synthetic health probe: feeds the detector
                    # (its quarantined-component probe path),
                    # never the SLO log
                    self._observe_health(
                        replica.replica_id, comp, now)
                    continue
                if "~a" in comp.request.request_id:
                    # integrity audit copy: feeds the vote, never
                    # the SLO log (the original already did)
                    self._on_audit_result(replica, comp, now)
                    continue
                if comp.finish_reason == "prefill_done":
                    # not a terminal outcome: the request's KV
                    # leaves for the decode pool; only the decode
                    # side's completion enters the log (one entry
                    # per request — the no-lost-work contract)
                    self._on_prefill_done(replica, comp, now)
                    continue
                self._handle_completion(replica, comp, now)
        for replica in list(self._draining):
            for comp in replica.tick(now, tick):
                if "~a" in comp.request.request_id:
                    self._on_audit_result(replica, comp, now)
                    continue
                if comp.finish_reason == "prefill_done":
                    self._on_prefill_done(replica, comp, now)
                    continue
                self._handle_completion(replica, comp, now)
            if replica.idle():
                self._draining.remove(replica)
                if self.sched is not None:
                    self.sched.release(
                        f"replica-{replica.replica_id}", now,
                        reason="scale-down drained")
        if self._ticks % self._eval_ticks == 0:
            if self.autoscaler is not None:
                self._autoscale(now)
            if self._pool_scalers is not None:
                self._autoscale_pools(now)
            if self.overload is not None:
                self.overload.brownout.evaluate(now)
            if self.trainer is not None:
                # the elastic ladder (no-op unless an elastic gang
                # is live, so skipped eval boundaries stay no-ops)
                self.trainer.evaluate(now)
        self._ticks += 1

    def quiescent(self, pending: Optional[deque] = None) -> bool:
        """Nothing pending, in flight, warming, draining, or left in
        the chaos plan — the loop's (and the globe driver's)
        termination test."""
        if pending is None:
            pending = self._pending
        return bool(
            not pending and not self.router.queue
            and not self._kv_heap and not self.router.kv_queue
            and not self._swap_heap
            and not self._audit_heap and not self._audits
            and not self._warming
            and (self._cols.all_idle() if self._cols is not None
                 else all(r.idle() for r in self.replicas
                          if r.healthy))
            and not self._draining
            and not self.chaos_events
            and not self._retry_heap and not self._hedge_heap
            and (self.trainer is None or self.trainer.quiescent())
            and not (self.sched is not None
                     and (self.sched.pending
                          or self._rebinding)))

    def _idle_gap(self, pending: deque) -> bool:
        """True when NOTHING can happen before the next arrival or
        chaos event: no queued/in-flight/warming/draining work, no
        scheduler activity, and no per-tick decision makers
        (autoscaler evaluations and health probes are tick-cadenced
        events, so their presence disqualifies the gap)."""
        if (self.autoscaler is not None or self.health is not None
                or self.overload is not None
                or self._pool_scalers is not None):
            return False
        if (self.trainer is not None
                and not self.trainer.quiescent()):
            return False
        if (self.router.queue or self._warming or self._draining):
            return False
        if (self._kv_heap or self.router.kv_queue
                or self._swap_heap):
            return False
        if self._audit_heap or self._audits:
            return False
        # slowdown != 1 disqualifies even an idle replica: an
        # EngineReplica's stride counter advances per tick() call,
        # so skipping ticks would shift its stepping phase
        if not all(r.idle()
                   and getattr(r, "slowdown", 1.0) == 1.0
                   for r in self.replicas):
            return False
        if self.sched is not None and (
                self.sched.pending or self._rebinding
                or self._gang_requested or self._migrate_pending):
            return False
        return True

    def _next_wake(self, pending: deque,
                   tick: float = 0.0) -> DueSet:
        """The event core's scheduling question: when does step()
        stop being a no-op? Sources that need every boundary (a
        non-empty router queue, scheduler activity, a draining
        replica, an engine-backed replica mid-stream) answer
        ``immediate``; timed sources (arrivals, chaos, warm-ups,
        probe deadlines) answer with boundary-condition times; the
        analytic replicas answer with closed-form in-slot event
        times the covering tick must process. Everything here is a
        pure read — the answer stays valid for exactly as long as no
        boundary is stepped, which is the invariant the skip loop
        relies on."""
        due = DueSet()
        if pending:
            due.at(pending[0].arrival_s)
        if self.chaos_events:
            ev0 = self.chaos_events[0]
            at = ev0.at_s
            if ev0.action in ("slow", "unslow", "link_degrade",
                              "link_restore"):
                # factor-change chaos rescales token scheduling from
                # the moment it applies, so the boundary BEFORE the
                # event must be stepped too: slots have to advance up
                # to it under the OLD factor, exactly as the plain
                # loop does, or the two cores schedule the straddling
                # tokens at different rates
                at = max(0.0, at - tick)
            due.at(at)
        # overload timers are boundary-condition events: a retry
        # applies at its backoff expiry, a hedge at its delay expiry
        due.at(self._retry_heap.peek_time())
        due.at(self._hedge_heap.peek_time())
        # a finished KV transfer applies at its delivery instant; a
        # queued handoff needs every boundary until the decode pool
        # takes it
        due.at(self._kv_heap.peek_time())
        # a finished model swap applies at its weight-load-ready
        # instant (bookkeeping drain into the swap ledger)
        due.at(self._swap_heap.peek_time())
        # a due integrity audit dispatches its duplicate-compute
        # copy at the original completion's finish instant
        due.at(self._audit_heap.peek_time())
        if self.trainer is not None:
            # gang arrivals and segment completions are boundary-
            # condition events; mid-segment progress is closed form
            self.trainer.due(due)
        if (self.router.queue or self.router.kv_queue
                or self._draining):
            return due.need_now()
        if self.sched is not None and (
                self.sched.pending or self._gang_requested
                or self._migrate_pending):
            return due.need_now()
        due.at(self._warming.peek_time())
        due.at(self._rebinding.peek_time())
        if self._cols is not None:
            ge, cover = self._cols.wake()
            due.at(ge)
            due.covering(cover)
            return self._wake_probes(due, pending)
        for replica in self.replicas:
            nd = getattr(replica, "next_due", None)
            if nd is None:
                # engine-backed (or foreign) replica: its stride
                # counter advances per tick() call, so only a
                # provably inert one may be skipped — the real-
                # ServingEngine tick mode stays the slow path
                if not (replica.idle()
                        and getattr(replica, "slowdown", 1.0)
                        == 1.0):
                    return due.need_now()
                continue
            ge, cover = nd()
            due.at(ge)
            due.covering(cover)
        return self._wake_probes(due, pending)

    def _wake_probes(self, due: DueSet, pending: deque) -> DueSet:
        if self.health is not None and pending:
            # probes fire while user traffic still flows, one per
            # suspect-or-quarantined alive replica per interval
            for replica in self.replicas:
                comp = f"replica-{replica.replica_id}"
                if (not replica.healthy
                        or self.health.state(comp) == "healthy"):
                    continue
                last = self._probe_last.get(comp)
                due.at(0.0 if last is None else
                       last + self.health.cfg.probe_interval_s)
        return due

    def _skip_uninteresting(self, tick: float,
                            pending: deque) -> None:
        """The event-core jump: having advanced to the next
        boundary, keep advancing (identical tick-sized float
        additions — a single n*tick jump would land on different
        floats) past every boundary where step() is provably a
        no-op. Skipped boundaries still count into the tick-grid
        index so the autoscaler's evaluation cadence lands on the
        identical boundaries as the plain loop."""
        # dense-path fast exits: when an arrival or chaos event is
        # already due at this boundary, it will be stepped no matter
        # what — don't pay the wake scan just to learn that
        b = self.clock.now()
        if pending and pending[0].arrival_s <= b:
            return
        if self._scan_holdoff > 0:
            self._scan_holdoff -= 1
            return
        if self.chaos_events and self.chaos_events[0].at_s <= b:
            return
        due = self._next_wake(pending, tick)
        if due.immediate:
            return
        evals_away = -1
        if (self.autoscaler is not None
                or self._pool_scalers is not None
                or self.overload is not None
                or (self.trainer is not None
                    and self.trainer.wants_evals())):
            # the overload brownout ladder and the training elastic
            # ladder evaluate on the same tick grid as the
            # autoscaler — eval boundaries must be stepped in both
            # modes or the ladders diverge
            r = self._ticks % self._eval_ticks
            evals_away = (self._eval_ticks - r) % self._eval_ticks
            if evals_away == 0:
                return  # this boundary IS an evaluation boundary
        due_ge = due.ge
        due_cover = due.cover
        limit = self.cfg.max_virtual_s
        adv = self.clock.advance
        now = self.clock.now
        skipped = 0
        while True:
            b = now()
            if b > limit or due_ge <= b or due_cover <= b + tick:
                break
            adv(tick)
            self._ticks += 1
            skipped += 1
            if evals_away > 0:
                evals_away -= 1
                if evals_away == 0:
                    break
        self.ev_skipped += skipped
        if skipped:
            self._scan_backoff = 1
        else:
            self._scan_holdoff = self._scan_backoff
            self._scan_backoff = min(self._scan_backoff * 2, 32)

    def _advance(self, tick: float, pending: deque) -> None:
        """Advance the clock one tick — then, with the event core
        enabled, jump past every provably uninteresting boundary
        (docs/PERFORMANCE.md "The event core"); or, on a provably
        idle gap with the legacy fast-forward enabled, through every
        empty tick up to the next arrival/chaos event. Either way
        the clock takes the IDENTICAL sequence of tick-sized float
        additions, so replays diff clean with the core (or ff) on or
        off; only the per-tick bookkeeping is skipped."""
        self.clock.advance(tick)
        if self._event_core:
            self._skip_uninteresting(tick, pending)
            return
        if not self._fast_forward or not self._idle_gap(pending):
            return
        next_s = pending[0].arrival_s if pending else float("inf")
        if self.chaos_events:
            next_s = min(next_s, self.chaos_events[0].at_s)
        limit = self.cfg.max_virtual_s
        adv = self.clock.advance
        now = self.clock.now
        while now() < next_s and now() <= limit:
            adv(tick)
            self.ff_skipped += 1

    def run(self) -> Dict[str, object]:
        board_before = metrics.fleet_board().counts()
        health_before = metrics.health_board().counts()
        disagg_before = metrics.disagg_board().counts()
        tenant_before = metrics.tenant_board().counts()
        zoo_before = metrics.zoo_board().counts()
        integrity_before = metrics.integrity_board().counts()
        tick = resolve_tick_s(self.cfg.tick_s)
        pending = self._pending
        while True:
            now = self.clock.now()
            if now > self.cfg.max_virtual_s:
                break
            self.step(now, tick, pending)
            if self.quiescent(pending):
                break
            self._advance(tick, pending)
        self.log.sort(key=lambda e: (e["finish_s"],
                                     e["request_id"]))
        report: Dict[str, object] = {
            "config": self.cfg.as_dict(),
            "requests": len(self.trace),
            "completed": len(self.log),
            "virtual_s": round(self.clock.now(), 6),
            "slo": self.tracker.report(span_s=self.clock.now()),
            "router": self.router.report(),
            "replicas": {
                str(r.replica_id): r.report()
                for r in sorted(self.replicas + self._draining,
                                key=lambda r: r.replica_id)},
            "completions": self.log,
            "fleet_counters": metrics.fleet_board().snapshot_since(
                board_before),
            "ok": len(self.log) == len(self.trace),
        }
        if self.overload is not None:
            # with client retries in play the log carries one entry
            # per ATTEMPT; the run is ok when every original request
            # reached a terminal outcome (its base id appears)
            base_done = {e["request_id"].split("~r", 1)[0]
                         for e in self.log}
            report["ok"] = all(r.request_id in base_done
                               for r in self.trace)
            report["overload"] = self.overload.report()
        if self.trainer is not None:
            tr = self.trainer.report()
            report["training"] = tr
            report["ok"] = bool(report["ok"] and tr["ledger_ok"])
        if self.tenancy is not None:
            ten_report = self.tenancy.report()
            ten_report["slo"] = {
                name: tracker.report(span_s=self.clock.now())
                for name, tracker in
                sorted(self._tenant_trackers.items())}
            ten_report["counters"] = (
                metrics.tenant_board().snapshot_since(
                    tenant_before))
            report["tenancy"] = ten_report
        if self._generations is not None:
            # per-replica generation labels (the pricing each
            # replica ran under) — conditional: generation-less
            # fleets keep their historical report bytes
            report["generations"] = {
                str(r.replica_id): self._gen_of(r.replica_id)
                for r in sorted(self.replicas + self._draining,
                                key=lambda r: r.replica_id)}
        if self._zoo is not None:
            report["zoo"] = {
                "per_model_slo": {
                    name: tracker.report(span_s=self.clock.now())
                    for name, tracker in
                    sorted(self._model_trackers.items())},
                "residents": {
                    str(r.replica_id): getattr(
                        r, "resident_model", "")
                    for r in sorted(self.replicas + self._draining,
                                    key=lambda r: r.replica_id)},
                "swaps": {
                    "completed": len(self._swap_log),
                    "log": self._swap_log,
                },
                "counters": metrics.zoo_board().snapshot_since(
                    zoo_before),
            }
        if self._sdc_active:
            # conditional: fleets that never saw an SDC fault (and
            # never enabled audits) keep their historical report
            # bytes — the byte-identical-replay contract
            report["integrity"] = {
                "audit_frac": round(self._audit_frac, 6),
                "detections": [
                    {"replica": rid, "at_s": t}
                    for rid, t in sorted(
                        self._sdc_detect_s.items())],
                "counters": metrics.integrity_board()
                .snapshot_since(integrity_before),
            }
        if self.preemptions:
            report["preemptions"] = self.preemptions
        if self.health is not None:
            report["health"] = {
                "detector": self.health.report(),
                "counters": metrics.health_board().snapshot_since(
                    health_before),
            }
        if self.autoscaler is not None:
            report["autoscaler"] = self.autoscaler.report()
        if self._disagg is not None:
            pools: Dict[str, dict] = {}
            for phase in ("prefill", "decode"):
                members = [
                    r for r in self.replicas + self._draining
                    if getattr(r, "phase", "unified") == phase]
                pools[phase] = {
                    "replicas": len(members),
                    "healthy": sum(1 for r in members
                                   if r.healthy),
                }
            report["disagg"] = {
                "config": self._disagg.as_dict(),
                "pools": pools,
                "kv": {
                    "handoffs": self._kv_handoffs,
                    "bytes_total": self._kv_bytes_total,
                    "transfer_s_total": round(
                        self._kv_transfer_s_total, 6),
                    "tier": self._disagg.tier,
                },
                "calibration_errors": self._cost.errors(),
                "counters": metrics.disagg_board()
                .snapshot_since(disagg_before),
            }
            if self._pool_scalers is not None:
                report["disagg"]["autoscalers"] = {
                    p: s.report() for p, s in
                    sorted(self._pool_scalers.items())}
        if self.sched is not None:
            ttrs = self.time_to_routable
            warmup = (self.autoscaler.warmup_s
                      if self.autoscaler is not None
                      else resolve_warmup_s())
            report["scheduler"] = {
                "policy": self._sched_cfg.policy,
                "flat_warmup_s": round(warmup, 6),
                "bind_s": self._sched_cfg.bind_s,
                "time_to_routable": {
                    "count": len(ttrs),
                    "mean_s": (round(sum(ttrs) / len(ttrs), 6)
                               if ttrs else None),
                    "max_s": (round(max(ttrs), 6)
                              if ttrs else None),
                },
                "events": self.sched.events,
                "event_counts":
                    self.sched.report()["event_counts"],
            }
        return report


def attainment_over(log: Sequence[dict], t_from: float,
                    t_to: float = float("inf")) -> Optional[float]:
    """SLO attainment restricted to requests ARRIVING in a window —
    how the chaos scenarios compare post-recovery service against the
    fault-free baseline without the backlog-drain period polluting
    the number."""
    window = [e for e in log
              if t_from <= e["arrival_s"] < t_to]
    if not window:
        return None
    return sum(1 for e in window if e["slo_ok"]) / len(window)
