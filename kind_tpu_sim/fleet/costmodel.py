"""Roofline-style analytic serving cost model (docs/DISAGG.md).

Prices the two serving phases from first principles, anchored to the
measured r05 bench artifacts instead of hand-tuned constants:

* **prefill** is compute-bound: the whole prompt runs through one
  forward pass, so prefill time is ``prompt_tokens`` over the
  MFU-capped forward rate (r05 measured 132k fwd tok/s at 58.6% MFU
  on v5e; the end-to-end serving prefill rate of 120k tok/s sits
  ~10% below it — sampling + host overhead — and that gap IS the
  reported prefill calibration error).
* **decode** is HBM-byte-bound: every generated token re-reads the
  weights plus the request's KV cache, so step time is
  ``weight_bytes / batch + kv_bytes(context)`` over the achieved
  HBM bandwidth (r05: 728 GB/s bf16 / 793 GB/s int8 against the
  819 GB/s roof — 89% / 97% of roofline).

The anchor numbers live in a checked-in calibration file
(``kind_tpu_sim/fleet/calibration/r05.json``), regenerated from any
``BENCH_LOCAL_*.json`` with :func:`calibrate` (the `fleet calibrate`
CLI). Per-phase analytic-vs-measured error is computed at calibration
time and pinned ≤15% by the test suite, so a model change that walks
away from the measurement fails loudly.

Everything here is pure float arithmetic over the calibration dict —
no clocks, no entropy — so any simulation built on a CostModel stays
replay-identical.
"""

from __future__ import annotations

import dataclasses
import json
import pathlib
import re
from typing import Dict, Optional

from kind_tpu_sim.analysis import knobs

CALIBRATION_ENV = knobs.CALIBRATION

# The pinned calibration-file schema (bump on any shape change; the
# loader refuses mismatches so a stale file cannot silently misprice).
CALIBRATION_SCHEMA = 1

DEFAULT_CALIBRATION = (pathlib.Path(__file__).parent
                       / "calibration" / "r05.json")
CALIBRATION_DIR = DEFAULT_CALIBRATION.parent

# -- the generation registry (docs/ZOO.md) ---------------------------
#
# One calibration file per accelerator generation
# (``calibration/<gen>.json``). ``v5e`` IS the measured r05 anchor
# (same numbers, plus the generation metadata block); ``v4`` and
# ``v5p`` are derived from it by the public roofline ratios — scaling
# the analytic AND measured rates by the same ratio preserves every
# ``error_frac``, so the ≤15% calibration-error bound holds for the
# derived files by construction (derive_generation is the one place
# the scaling rule lives; the checked-in files are pinned against it
# by the test suite).

DEFAULT_GENERATION = "v5e"
GENERATIONS = ("v5e", "v4", "v5p")

# topology.ACCELERATORS label -> generation name: how a sched
# inventory pool's accelerator label (pods/*.yaml nodeSelector,
# kubeface) resolves to the calibration that prices its replicas.
ACCELERATOR_GENERATIONS = {
    "tpu-v5-lite-podslice": "v5e",
    "tpu-v4-podslice": "v4",
    "tpu-v5p-slice": "v5p",
}

# the inverse: generation name -> the accelerator label a sched
# inventory pool of that generation requests (mixed-generation globe
# cells build their FleetSchedConfig from it)
GENERATION_ACCELERATORS = {
    gen: accel for accel, gen in ACCELERATOR_GENERATIONS.items()}

# sched inventory shapes per accelerator: (pod topology, replica
# slice topology) — v5e pods are 2-D (4x8 hosts a 2x4 replica slice);
# v4/v5p topologies are 3-D (topology.AcceleratorSpec.ndims)
GENERATION_SCHED_TOPOLOGY = {
    "tpu-v5-lite-podslice": ("4x8", "2x4"),
    "tpu-v4-podslice": ("4x4x4", "2x2x2"),
    "tpu-v5p-slice": ("4x4x4", "2x2x2"),
}

# Public per-chip facts vs the v5e anchor (197 bf16 TFLOPs, 819 GB/s
# HBM, 16 GiB): v4 = 275 TFLOPs / 1228 GB/s / 32 GiB, v5p = 459
# TFLOPs / 2765 GB/s / 95 GiB. chip_second_cost is the relative
# on-demand price per chip-second (v5e = 1.0) the tune cost axis
# weights mixed-generation fleets by.
GENERATION_FACTS = {
    "v5e": {"compute_ratio": 1.0, "bandwidth_ratio": 1.0,
            "hbm_gib": 16.0, "chip_second_cost": 1.0},
    "v4": {"compute_ratio": 1.396, "bandwidth_ratio": 1.499,
           "hbm_gib": 32.0, "chip_second_cost": 2.7},
    "v5p": {"compute_ratio": 2.33, "bandwidth_ratio": 3.376,
            "hbm_gib": 95.0, "chip_second_cost": 3.5},
}

DTYPES = ("bf16", "int8")
DTYPE_BYTES = {"bf16": 2, "int8": 1}

# BENCH_LOCAL_*.json `/model/*` keys calibrate() refuses to run
# without — a bench round that dropped its roofline sweep cannot
# produce a calibration file by accident.
REQUIRED_MODEL_KEYS = (
    "backend", "chip", "decode_roofline", "decode_tokens_per_s",
    "decode_int8_roofline", "decode_int8_tokens_per_s",
    "fwd_tokens_per_s", "model", "prefill_tokens_per_s", "serving",
)
REQUIRED_ROOFLINE_KEYS = (
    "achieved_gbps", "bytes_per_step_mb", "kv_mb", "roof_gbps",
    "weight_mb",
)

_GEOMETRY_RE = re.compile(r"^d(\d+)xL(\d+)(?:-gqa(\d+))?$")


def parse_geometry(model: str) -> Dict[str, int]:
    """Decode the bench model string (``d2048xL8-gqa4``) into the
    dimensions the KV-cache size depends on."""
    m = _GEOMETRY_RE.match(model)
    if m is None:
        raise ValueError(
            f"unparseable model geometry {model!r} (expected "
            "d<d_model>xL<layers>[-gqa<group>])")
    return {
        "d_model": int(m.group(1)),
        "layers": int(m.group(2)),
        "gqa": int(m.group(3) or 1),
    }


def kv_bytes_per_token(geometry: Dict[str, int], dtype: str) -> int:
    """KV-cache bytes one context token occupies: K and V, every
    layer, at the grouped-query head width."""
    if dtype not in DTYPE_BYTES:
        raise ValueError(
            f"unknown dtype {dtype!r}; known: {', '.join(DTYPES)}")
    return (2 * geometry["layers"]
            * (geometry["d_model"] // geometry["gqa"])
            * DTYPE_BYTES[dtype])


def _error_frac(analytic: float, measured: float) -> float:
    return round(abs(analytic - measured) / measured, 6)


def calibrate(bench: dict) -> dict:
    """Derive the calibration file contents from one
    ``BENCH_LOCAL_*.json`` report. Fails loudly (ValueError naming
    every absent key) when the bench round lacks the roofline
    sweeps — a partial bench must not recalibrate the fleet."""
    model = bench.get("model")
    if not isinstance(model, dict):
        raise ValueError(
            "bench report has no top-level 'model' block — not a "
            "BENCH_LOCAL_*.json roofline round")
    missing = [k for k in REQUIRED_MODEL_KEYS if k not in model]
    for roof_key in ("decode_roofline", "decode_int8_roofline"):
        roof = model.get(roof_key)
        if isinstance(roof, dict):
            missing.extend(f"{roof_key}.{k}"
                           for k in REQUIRED_ROOFLINE_KEYS
                           if k not in roof)
    if missing:
        raise ValueError(
            "bench model block is missing roofline key(s): "
            + ", ".join(sorted(missing)))
    slots = int(model["serving"].get("slots", 1))
    geometry = parse_geometry(model["model"])

    # prefill: the analytic roof is the pure forward pass (what the
    # compute roofline prices); the measured serving prefill rate
    # sits below it by the sampling/host overhead the model omits.
    fwd = float(model["fwd_tokens_per_s"])
    prefill_measured = float(model["prefill_tokens_per_s"])

    decode: Dict[str, dict] = {}
    for dtype, roof_key, rate_key in (
            ("bf16", "decode_roofline", "decode_tokens_per_s"),
            ("int8", "decode_int8_roofline",
             "decode_int8_tokens_per_s")):
        roof = model[roof_key]
        measured = float(model[rate_key])
        # bytes/step is the whole batch's read set; one step emits
        # one token per slot, so the analytic aggregate rate is
        # slots x achieved bytes/s over bytes/step
        analytic = (slots * float(roof["achieved_gbps"]) * 1e9
                    / (float(roof["bytes_per_step_mb"]) * 1e6))
        decode[dtype] = {
            "achieved_gbps": float(roof["achieved_gbps"]),
            "analytic_tokens_per_s": round(analytic, 3),
            "bytes_per_step_mb": float(roof["bytes_per_step_mb"]),
            "error_frac": _error_frac(analytic, measured),
            "kv_mb": float(roof["kv_mb"]),
            "measured_tokens_per_s": measured,
            "roof_gbps": float(roof["roof_gbps"]),
            "weight_mb": float(roof["weight_mb"]),
        }

    return {
        "schema": CALIBRATION_SCHEMA,
        "backend": str(model["backend"]),
        "chip": str(model["chip"]),
        "model": str(model["model"]),
        "geometry": geometry,
        "slots": slots,
        "prefill": {
            "analytic_tokens_per_s": fwd,
            "measured_tokens_per_s": prefill_measured,
            "error_frac": _error_frac(fwd, prefill_measured),
        },
        "decode": decode,
    }


def load_calibration(path: Optional[str] = None) -> dict:
    """Load a calibration file: explicit path > the
    ``KIND_TPU_SIM_CALIBRATION`` knob > the checked-in r05 file."""
    if path is None:
        path = knobs.get(CALIBRATION_ENV)
    if path is None:
        path = str(DEFAULT_CALIBRATION)
    with open(path, encoding="utf-8") as fh:
        cal = json.load(fh)
    if cal.get("schema") != CALIBRATION_SCHEMA:
        raise ValueError(
            f"calibration file {path} has schema "
            f"{cal.get('schema')!r}; this build expects "
            f"{CALIBRATION_SCHEMA} — regenerate with "
            "`kind-tpu-sim fleet calibrate`")
    return cal


def generation_path(name: str) -> pathlib.Path:
    """Where generation ``name``'s calibration file lives."""
    return CALIBRATION_DIR / f"{name}.json"


def load_generation(name: str) -> dict:
    """Load a registered generation's calibration by name. The file
    must self-identify (``generation`` key matching its stem) so a
    renamed or mis-derived file cannot silently misprice a fleet."""
    if name not in GENERATIONS:
        raise ValueError(
            f"unknown generation {name!r}; registered: "
            f"{', '.join(GENERATIONS)}")
    cal = load_calibration(str(generation_path(name)))
    if cal.get("generation") != name:
        raise ValueError(
            f"calibration file {generation_path(name)} declares "
            f"generation {cal.get('generation')!r}, expected "
            f"{name!r} — regenerate with `kind-tpu-sim fleet "
            "calibrate`")
    return cal


def generation_of_accelerator(accelerator: str) -> str:
    """The generation name a sched/kubeface accelerator label prices
    against (``tpu-v5-lite-podslice`` -> ``v5e``)."""
    try:
        return ACCELERATOR_GENERATIONS[accelerator]
    except KeyError:
        raise ValueError(
            f"accelerator {accelerator!r} maps to no registered "
            f"generation; known: "
            f"{', '.join(sorted(ACCELERATOR_GENERATIONS))}") from None


def derive_generation(base: dict, name: str) -> dict:
    """Scale the measured anchor calibration onto generation ``name``
    by its public roofline ratios. Prefill (compute-bound) rates
    scale by the compute ratio; decode (HBM-byte-bound) bandwidths
    and rates scale by the bandwidth ratio. The analytic and measured
    sides of each phase scale together, so every ``error_frac`` is
    preserved — the derived file inherits the anchor's calibration
    quality instead of inventing its own."""
    facts = GENERATION_FACTS[name]
    compute = facts["compute_ratio"]
    bw = facts["bandwidth_ratio"]
    slots = int(base["slots"])
    prefill_analytic = round(
        base["prefill"]["analytic_tokens_per_s"] * compute, 3)
    prefill_measured = round(
        base["prefill"]["measured_tokens_per_s"] * compute, 3)
    decode: Dict[str, dict] = {}
    for dtype, d in base["decode"].items():
        achieved = round(d["achieved_gbps"] * bw, 3)
        analytic = (slots * achieved * 1e9
                    / (d["bytes_per_step_mb"] * 1e6))
        measured = round(d["measured_tokens_per_s"] * bw, 3)
        decode[dtype] = {
            "achieved_gbps": achieved,
            "analytic_tokens_per_s": round(analytic, 3),
            "bytes_per_step_mb": d["bytes_per_step_mb"],
            "error_frac": _error_frac(analytic, measured),
            "kv_mb": d["kv_mb"],
            "measured_tokens_per_s": measured,
            "roof_gbps": round(d["roof_gbps"] * bw, 3),
            "weight_mb": d["weight_mb"],
        }
    return {
        "schema": CALIBRATION_SCHEMA,
        "backend": base["backend"],
        "chip": name,
        "generation": name,
        "hbm_gib": facts["hbm_gib"],
        "chip_second_cost": facts["chip_second_cost"],
        "model": base["model"],
        "geometry": dict(base["geometry"]),
        "slots": slots,
        "prefill": {
            "analytic_tokens_per_s": prefill_analytic,
            "measured_tokens_per_s": prefill_measured,
            "error_frac": base["prefill"]["error_frac"],
        },
        "decode": decode,
    }


@dataclasses.dataclass(frozen=True)
class RequestCost:
    """One request priced end to end (virtual seconds + the KV bytes
    a disaggregated handoff would ship)."""

    prefill_s: float
    decode_s: float
    kv_bytes: int

    @property
    def total_s(self) -> float:
        return self.prefill_s + self.decode_s

    def as_dict(self) -> dict:
        return {
            "prefill_s": round(self.prefill_s, 9),
            "decode_s": round(self.decode_s, 9),
            "kv_bytes": self.kv_bytes,
            "total_s": round(self.total_s, 9),
        }


class CostModel:
    """Analytic per-request pricing over one calibration dict. The
    public methods are all pure functions of their arguments, and
    prefill time is monotone in prompt tokens / decode time monotone
    in KV bytes by construction (the property tests pin both)."""

    def __init__(self, calibration: Optional[dict] = None):
        self.cal = (calibration if calibration is not None
                    else load_calibration())
        self.geometry = self.cal["geometry"]

    def kv_bytes(self, prompt_tokens: int,
                 dtype: str = "bf16") -> int:
        """The KV cache a prefilled prompt occupies — what a
        prefill->decode handoff ships over ICI/DCN."""
        return (max(0, int(prompt_tokens))
                * kv_bytes_per_token(self.geometry, dtype))

    def prefill_s(self, prompt_tokens: int, batch: int = 1,
                  dtype: str = "bf16") -> float:
        """Compute-bound: tokens over the MFU-capped forward rate.
        Batching doesn't change aggregate prefill throughput (the
        pass is already compute-saturated), so per-request time is
        batch-independent; dtype rides the same systolic path."""
        del batch, dtype
        rate = float(self.cal["prefill"]["analytic_tokens_per_s"])
        return max(0, int(prompt_tokens)) / rate

    def decode_step_s(self, context_tokens: int, batch: int = 1,
                      dtype: str = "bf16") -> float:
        """Byte-bound: one token per slot costs the weight read
        (amortized over the batch) plus this request's KV read,
        over the achieved HBM bandwidth."""
        d = self.cal["decode"][dtype]
        step_bytes = (d["weight_mb"] * 1e6 / max(1, batch)
                      + self.kv_bytes(context_tokens, dtype))
        return step_bytes / (d["achieved_gbps"] * 1e9)

    def decode_s(self, gen_tokens: int, context_tokens: int,
                 batch: int = 1, dtype: str = "bf16") -> float:
        """Whole-generation decode time at a fixed context (the KV
        growth over a short generation is second-order against the
        weight read; the monotonicity properties hold either way)."""
        return (max(0, int(gen_tokens))
                * self.decode_step_s(context_tokens, batch=batch,
                                     dtype=dtype))

    def request_cost(self, prompt_tokens: int, gen_tokens: int,
                     batch: int = 1,
                     dtype: str = "bf16") -> RequestCost:
        return RequestCost(
            prefill_s=self.prefill_s(prompt_tokens, batch=batch,
                                     dtype=dtype),
            decode_s=self.decode_s(gen_tokens, prompt_tokens,
                                   batch=batch, dtype=dtype),
            kv_bytes=self.kv_bytes(prompt_tokens, dtype))

    def errors(self) -> Dict[str, float]:
        """Per-phase analytic-vs-measured error fractions on the
        calibration points — the bench extra the ≤15% bound pins."""
        return {
            "prefill": self.cal["prefill"]["error_frac"],
            "decode_bf16": self.cal["decode"]["bf16"]["error_frac"],
            "decode_int8": self.cal["decode"]["int8"]["error_frac"],
        }
