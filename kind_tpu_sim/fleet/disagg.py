"""Disaggregated prefill/decode serving (docs/DISAGG.md).

The fleet's replicas grow a *phase* role: a ``prefill`` replica runs
prompts through prefill only and hands the finished request — really
its KV cache — to a ``decode`` replica over a modeled interconnect
transfer; a ``decode`` replica runs token generation only; the default
``unified`` role is the pre-existing monolithic engine, byte-identical
to every historical replay. This mirrors the production TPU serving
architecture (PAPERS.md: separately scaled prefill and decode pools
with KV-cache handoff) at fleet-sim granularity.

Three pieces live here:

* :class:`DisaggConfig` — declares the pool split (``P:D``), the
  KV-transfer interconnect tier (``ici`` intra-cell / ``dcn``
  cross-cell, priced off ``parallel.collectives.TIER_LINK_GBPS``),
  and the serving dtype (``bf16`` / ``int8`` — int8 halves both the
  decode byte roof and the shipped KV bytes, as r05 measured).
* :class:`KvHandoff` — the unit of work in flight between pools: the
  original request plus its prefill outcome (dispatch/first-token
  stamps, KV bytes). It duck-types the ``TraceRequest`` fields the
  router needs so decode-pool placement reuses the same machinery.
* :func:`calibrated_sim_config` — derives a ``SimReplicaConfig`` from
  a :mod:`costmodel` calibration file, so the analytic replicas price
  prefill and decode at the measured r05 rates instead of the
  hand-tuned defaults.
"""

from __future__ import annotations

import dataclasses
from typing import ClassVar, Optional, Tuple

from kind_tpu_sim.analysis import knobs
from kind_tpu_sim.fleet.loadgen import TraceRequest
from kind_tpu_sim.parallel.collectives import TIER_LINK_GBPS

PHASES = ("prefill", "decode", "unified")
KV_TIERS = tuple(sorted(TIER_LINK_GBPS))

DISAGG_TIER_ENV = knobs.DISAGG_TIER
DISAGG_DTYPE_ENV = knobs.DISAGG_DTYPE


def resolve_tier(value: Optional[str] = None) -> str:
    """Explicit value > env (KIND_TPU_SIM_DISAGG_TIER) > ici."""
    tier = value if value is not None else knobs.get(DISAGG_TIER_ENV)
    if tier not in TIER_LINK_GBPS:
        raise ValueError(
            f"unknown KV-transfer tier {tier!r}; known: "
            f"{', '.join(KV_TIERS)}")
    return tier


def resolve_dtype(value: Optional[str] = None) -> str:
    """Explicit value > env (KIND_TPU_SIM_DISAGG_DTYPE) > bf16."""
    from kind_tpu_sim.fleet.costmodel import DTYPE_BYTES, DTYPES

    dtype = (value if value is not None
             else knobs.get(DISAGG_DTYPE_ENV))
    if dtype not in DTYPE_BYTES:
        raise ValueError(
            f"unknown serving dtype {dtype!r}; known: "
            f"{', '.join(DTYPES)}")
    return dtype


def kv_transfer_s(kv_bytes: int, tier: str,
                  factor: float = 1.0) -> float:
    """Time to ship one request's KV cache between pools over the
    named interconnect tier. ``factor`` is the chaos lever
    (``kv_transfer_degrade``): effective bandwidth scales by it, so
    0.2 means the link runs at a fifth of nominal."""
    if tier not in TIER_LINK_GBPS:
        raise ValueError(
            f"unknown KV-transfer tier {tier!r}; known: "
            f"{', '.join(KV_TIERS)}")
    # TIER_LINK_GBPS is gigaBITS/s (the collectives convention)
    bytes_per_s = TIER_LINK_GBPS[tier] * 1e9 / 8.0 * factor
    return max(0, int(kv_bytes)) / bytes_per_s


@dataclasses.dataclass(frozen=True)
class DisaggConfig:
    """The phase-split declaration a fleet run opts into.

    ``prefill_replicas : decode_replicas`` is the pool ratio (the
    ``--disagg P:D`` CLI flag); the fleet's total replica count is
    their sum. ``calibrated`` derives the analytic replicas' service
    rates from the checked-in r05 calibration instead of the
    hand-tuned ``SimReplicaConfig`` defaults."""

    enabled: bool = True
    prefill_replicas: int = 1
    decode_replicas: int = 1
    tier: str = "ici"
    dtype: str = "bf16"
    calibrated: bool = True

    def __post_init__(self):
        if self.prefill_replicas < 1 or self.decode_replicas < 1:
            raise ValueError(
                "disagg needs at least one replica per pool "
                f"(got {self.prefill_replicas}:"
                f"{self.decode_replicas})")
        resolve_tier(self.tier)
        resolve_dtype(self.dtype)

    @classmethod
    def parse(cls, spec: str, *, tier: Optional[str] = None,
              dtype: Optional[str] = None) -> "DisaggConfig":
        """Build from the CLI's ``P:D`` ratio string."""
        parts = spec.split(":")
        if len(parts) != 2:
            raise ValueError(
                f"--disagg wants P:D (e.g. 2:2), got {spec!r}")
        try:
            p, d = int(parts[0]), int(parts[1])
        except ValueError:
            raise ValueError(
                f"--disagg wants integer P:D, got {spec!r}") from None
        return cls(prefill_replicas=p, decode_replicas=d,
                   tier=resolve_tier(tier),
                   dtype=resolve_dtype(dtype))

    def as_dict(self) -> dict:
        return {
            "enabled": self.enabled,
            "prefill_replicas": self.prefill_replicas,
            "decode_replicas": self.decode_replicas,
            "tier": self.tier,
            "dtype": self.dtype,
            "calibrated": self.calibrated,
        }


@dataclasses.dataclass(frozen=True)
class KvHandoff:
    """One prefilled request in flight from the prefill pool to the
    decode pool. Carries the prefill outcome (dispatch and
    first-token stamps survive the transfer — TTFT is a property of
    the request, not of the decode replica) and the KV bytes the
    transfer ships. Duck-types the ``TraceRequest`` surface the
    router's placement path reads, so decode-pool dispatch reuses
    the ordinary machinery."""

    is_kv_handoff: ClassVar[bool] = True

    request: TraceRequest
    dispatch_s: float
    first_s: float
    tokens: int
    kv_bytes: int
    from_replica: int

    @property
    def request_id(self) -> str:
        return self.request.request_id

    @property
    def arrival_s(self) -> float:
        return self.request.arrival_s

    @property
    def deadline_s(self) -> Optional[float]:
        return self.request.deadline_s

    @property
    def prefix_group(self) -> int:
        return self.request.prefix_group

    @property
    def prompt(self) -> Tuple[int, ...]:
        return self.request.prompt

    @property
    def max_new(self) -> int:
        return self.request.max_new

    @property
    def seed(self) -> int:
        return self.request.seed

    @property
    def tenant(self) -> str:
        return self.request.tenant

    @property
    def user_id(self) -> int:
        return self.request.user_id

    @property
    def model(self) -> str:
        return self.request.model


def calibrated_sim_config(cal: dict, dtype: str = "bf16",
                          max_slots: int = 8,
                          max_queue: int = 64,
                          prefix_cache_entries: int = 8):
    """A ``SimReplicaConfig`` priced off a calibration file: prefill
    per-token time from the measured forward rate, TPOT from the
    decode byte roofline at this slot count (weight read amortized
    over the batch, plus the calibration point's per-request KV
    read, over achieved HBM bytes/s)."""
    from kind_tpu_sim.fleet.router import SimReplicaConfig

    prefill_rate = float(cal["prefill"]["analytic_tokens_per_s"])
    d = cal["decode"][dtype]
    slots = max(1, int(max_slots))
    kv_per_req_bytes = d["kv_mb"] * 1e6 / max(1, int(cal["slots"]))
    step_bytes = (d["weight_mb"] * 1e6 / slots + kv_per_req_bytes)
    tpot = step_bytes / (d["achieved_gbps"] * 1e9)
    return SimReplicaConfig(
        max_slots=slots,
        prefill_base_s=0.0,
        prefill_per_tok_s=1.0 / prefill_rate,
        tpot_s=round(tpot, 9),
        max_queue=max_queue,
        prefix_cache_entries=prefix_cache_entries,
    )
