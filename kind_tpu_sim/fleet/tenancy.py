"""Multi-tenant serving: the millions-of-users realism layer.

The north star's "heavy traffic from millions of users" needs more
than anonymous poisson arrivals: real fleets serve a heavy-tailed
POPULATION — a few tenants (and a few users inside each tenant)
produce most of the traffic, users issue multi-request sessions, and
each user's requests share prompt prefixes. This module owns that
model plus the isolation machinery that keeps one tenant's burst from
becoming another tenant's p99 (docs/TENANCY.md):

* :class:`TenantSpec` / :class:`TenancyConfig` — the declared tenant
  population: QoS tier (``interactive`` / ``standard`` / ``batch``),
  weighted-fair share, user count with Zipf per-user rates, session
  shape, and admission quotas (request-rate and token-metered).
* :func:`generate_tenant_trace` — the seeded heavy-tailed workload:
  Lewis thinning for arrivals (the untenanted algorithm), tenants
  drawn by ``rps_share``, users by Zipf, sessions of think-time-
  spaced requests, per-(tenant, user) prefix cohorts. A pure
  function of (spec, seed); plain untenanted specs never reach this
  path, so every pre-tenancy stream is byte-identical.
* :class:`RateBucket` — the PR 9 :class:`TokenBucket` refilled by
  VIRTUAL TIME instead of per-event earns: quotas are rates, and the
  refill is closed-form in ``now`` so event-core boundary skipping
  cannot change an admission verdict.
* :class:`TenancyState` — one sim's live tenancy state: per-tenant
  quota buckets, admission verdicts, per-tenant shed counters, and
  the weights/tiers the router's deficit-round-robin queuing and the
  brownout ladder read.
* :func:`tenant_surge_trace` — the ``noisy_neighbor`` /
  ``tenant_surge`` fault kinds' trace transform: extra arrivals from
  ONE tenant confined to a window, drawn from a sub-seed the
  ChaosSchedule way so the surge is byte-stable and the base trace
  untouched.

Determinism: every draw comes from the spec-keyed stream or a
crc32-derived sub-stream; quota refills are pure functions of the
virtual clock; DRR state advances only on placements. Same (config,
seed) twice — byte-identical reports, isolation on or off.
"""

from __future__ import annotations

import bisect
import dataclasses
import random
import zlib
from typing import Dict, List, Optional, Tuple

from kind_tpu_sim.analysis import knobs
from kind_tpu_sim.fleet.overload import TokenBucket

TENANT_ISOLATION_ENV = knobs.TENANT_ISOLATION
TENANT_DRR_QUANTUM_ENV = knobs.TENANT_DRR_QUANTUM

# QoS ladder, best first. Rank is strict priority at the router (an
# ``interactive`` request never waits behind ``batch`` backlog);
# within one rank tenants share by deficit round robin. ``batch`` is
# the scavenger tier: brownout sheds it first (the declared-tier
# unification of the request_tier ladder, docs/OVERLOAD.md).
QOS_TIERS = ("interactive", "standard", "batch")


def resolve_isolation(value: Optional[bool] = None) -> bool:
    """Explicit value > env (KIND_TPU_SIM_TENANT_ISOLATION) > on."""
    if value is not None:
        return bool(value)
    return bool(knobs.get(TENANT_ISOLATION_ENV))


def resolve_drr_quantum(value: Optional[float] = None) -> float:
    """Explicit value > env (KIND_TPU_SIM_TENANT_DRR_QUANTUM) >
    4.0 (requests credited per DRR visit per unit weight)."""
    if value is not None:
        return float(value)
    return float(knobs.get(TENANT_DRR_QUANTUM_ENV))


@dataclasses.dataclass(frozen=True)
class TenantSpec:
    """One tenant's declaration: who they are to the traffic model
    (share, users, sessions) and to the isolation machinery (QoS
    tier, weight, quotas, KV budget).

    Quotas of 0 mean unlimited (the bucket is disabled — every
    admission succeeds, the controls-off shape ``TokenBucket``
    already has). ``kv_budget_frac`` >= 1 means no decode-pool KV
    cap."""

    name: str
    qos: str = "standard"
    # deficit-round-robin weight within the tenant's QoS rank
    weight: float = 1.0
    # share of the spec's aggregate rps this tenant contributes
    rps_share: float = 1.0
    # user population: per-user request rates are Zipf(zipf_a) over
    # ranks, the heavy tail that makes "millions of users" mostly a
    # few thousand hot ones
    users: int = 100
    zipf_a: float = 1.1
    # each drawn arrival opens a session of [lo, hi] requests spaced
    # think_time_s apart (closed-loop structure inside an open-loop
    # trace)
    session_len: Tuple[int, int] = (1, 3)
    think_time_s: float = 0.2
    # admission quotas (enforced at the front door / fleet edge):
    # request-rate and token-metered (prompt + max_new) rates with
    # burst capacity; 0 disables
    quota_rps: float = 0.0
    quota_burst: float = 8.0
    token_quota_per_s: float = 0.0
    token_quota_burst: float = 512.0
    # share of a decode replica's prefix/KV capacity this tenant may
    # occupy (docs/DISAGG.md); >= 1 uncapped
    kv_budget_frac: float = 1.0

    def __post_init__(self):
        if self.qos not in QOS_TIERS:
            raise ValueError(
                f"unknown qos tier {self.qos!r}; known: "
                f"{', '.join(QOS_TIERS)}")
        if self.weight <= 0:
            raise ValueError(
                f"tenant {self.name!r} weight must be > 0 "
                f"(got {self.weight})")
        if self.rps_share <= 0:
            raise ValueError(
                f"tenant {self.name!r} rps_share must be > 0 "
                f"(got {self.rps_share})")
        if self.users < 1:
            raise ValueError(
                f"tenant {self.name!r} needs at least one user")

    def as_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["session_len"] = list(self.session_len)
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "TenantSpec":
        d = dict(d)
        d["session_len"] = tuple(d["session_len"])
        return cls(**d)


# Requests arriving without a declared tenant under a tenancy-on sim
# (hand-built traces, surge extras from pre-tenancy transforms) fall
# back to this spec: standard tier, weight 1, no quotas.
DEFAULT_TENANT = TenantSpec(name="default")


@dataclasses.dataclass(frozen=True)
class TenancyConfig:
    """The declared tenant population plus the isolation switches.
    ``isolation=False`` keeps the traffic model but turns OFF quotas,
    DRR, and KV budgets — the contrast run the noisy-neighbor
    scenario proves the controls against."""

    tenants: Tuple[TenantSpec, ...] = ()
    isolation: Optional[bool] = None
    drr_quantum: Optional[float] = None

    def __post_init__(self):
        if not self.tenants:
            raise ValueError("TenancyConfig needs >= 1 tenant")
        names = [t.name for t in self.tenants]
        if len(set(names)) != len(names):
            raise ValueError(
                f"duplicate tenant names: {sorted(names)}")

    def lookup(self, name: str) -> TenantSpec:
        for t in self.tenants:
            if t.name == name:
                return t
        return DEFAULT_TENANT

    def qos_rank(self, name: str) -> int:
        return QOS_TIERS.index(self.lookup(name).qos)

    def weight(self, name: str) -> float:
        return self.lookup(name).weight

    def tier(self, name: str) -> int:
        """The brownout ladder's DECLARED tier: batch is the
        sheddable tier 1, everything else tier 0 — replacing the
        id-hash ``request_tier`` when tenancy is on."""
        return 1 if self.lookup(name).qos == "batch" else 0

    def signature(self) -> tuple:
        """The loadgen identity key contribution: only the fields
        that shape the TRAFFIC join (share, users, sessions), so
        changing a quota or weight compares policies on the byte-
        identical trace."""
        return tuple(
            (t.name, t.rps_share, t.users, t.zipf_a,
             tuple(t.session_len), t.think_time_s)
            for t in self.tenants)

    def without_quotas(self) -> "TenancyConfig":
        """The cell-tier copy for globe runs: quotas live at the
        front door (the client tier) and must not be charged twice,
        while DRR and KV budgets stay with the cell routers."""
        return dataclasses.replace(self, tenants=tuple(
            dataclasses.replace(t, quota_rps=0.0,
                                token_quota_per_s=0.0)
            for t in self.tenants))

    def as_dict(self) -> dict:
        out: Dict[str, object] = {
            "tenants": [t.as_dict() for t in self.tenants],
            "isolation": resolve_isolation(self.isolation),
            "drr_quantum": resolve_drr_quantum(self.drr_quantum),
        }
        return out

    @classmethod
    def from_dict(cls, d: dict) -> "TenancyConfig":
        return cls(
            tenants=tuple(TenantSpec.from_dict(t)
                          for t in d["tenants"]),
            isolation=d.get("isolation"),
            drr_quantum=d.get("drr_quantum"))


def default_tenancy() -> TenancyConfig:
    """The stock three-tenant population the fuzzer and declarative
    specs use when a drawn spec turns tenancy on: one interactive
    tenant, one standard, one quota-bounded batch scavenger."""
    return TenancyConfig(tenants=(
        TenantSpec(name="gold", qos="interactive", weight=4.0,
                   rps_share=0.3, users=50, zipf_a=1.2),
        TenantSpec(name="silver", qos="standard", weight=2.0,
                   rps_share=0.4, users=200),
        TenantSpec(name="bronze", qos="batch", weight=1.0,
                   rps_share=0.3, users=1000,
                   quota_rps=40.0, quota_burst=20.0),
    ))


def tenant_of(req) -> str:
    """The request's declared tenant, ``default`` when absent — so
    hand-built untenanted traces still run under a tenancy-on sim."""
    return getattr(req, "tenant", "") or "default"


# -- quota buckets -----------------------------------------------------


class RateBucket(TokenBucket):
    """The PR 9 :class:`TokenBucket` as a RATE limiter: tokens refill
    continuously at ``rate_per_s`` of VIRTUAL time (closed-form in
    ``now``, so boundary skipping never changes a verdict) and a
    ``take`` may spend a fractional ``cost`` — the token-metered
    quota charges ``prompt + max_new`` per request. ``rate_per_s``
    <= 0 disables the bucket (every take succeeds), the same
    controls-off shape as the parent."""

    __slots__ = ("rate_per_s", "_last_s")

    def __init__(self, rate_per_s: float, burst: float):
        super().__init__(
            ratio=(1.0 if rate_per_s > 0 else 0.0), burst=burst)
        self.rate_per_s = float(rate_per_s)
        self._last_s = 0.0

    def refill(self, now: float) -> None:
        if self.disabled:
            return
        dt = now - self._last_s
        if dt > 0:
            self.tokens = min(self.burst,
                              self.tokens + self.rate_per_s * dt)
            self._last_s = now

    def take(self, now: float, cost: float = 1.0) -> bool:
        if self.disabled:
            self.spent += 1
            return True
        self.refill(now)
        if self.tokens >= cost:
            self.tokens -= cost
            self.spent += 1
            return True
        self.suppressed += 1
        return False

    def report(self) -> Dict[str, object]:
        out = super().report()
        out["rate_per_s"] = self.rate_per_s
        return out


class TenancyState:
    """One sim's live tenancy state: per-tenant admission quotas
    (request-rate and token-metered), per-tenant admission/shed
    counters, and the declared weights/ranks/tiers the router and
    brownout read. Buckets are created lazily per tenant OBSERVED —
    a pure function of the trace, so reports stay byte-identical."""

    def __init__(self, cfg: TenancyConfig):
        self.cfg = cfg
        self.isolation = resolve_isolation(cfg.isolation)
        self.drr_quantum = resolve_drr_quantum(cfg.drr_quantum)
        self._quota: Dict[str, RateBucket] = {}
        self._token_quota: Dict[str, RateBucket] = {}
        self.admitted: Dict[str, int] = {}
        self.quota_shed: Dict[str, int] = {}
        self.token_shed: Dict[str, int] = {}
        self.kv_deferred: Dict[str, int] = {}

    # -- declared-policy accessors ------------------------------------

    def qos_rank(self, name: str) -> int:
        return self.cfg.qos_rank(name)

    def weight(self, name: str) -> float:
        return self.cfg.weight(name)

    def tier(self, name: str) -> int:
        return self.cfg.tier(name)

    def kv_budget(self, name: str, capacity: int) -> Optional[int]:
        """The tenant's decode-pool occupancy cap out of
        ``capacity`` units (slots or cache entries); None = uncapped
        (frac >= 1 or isolation off)."""
        if not self.isolation:
            return None
        frac = self.cfg.lookup(name).kv_budget_frac
        if frac >= 1.0:
            return None
        return max(1, int(frac * capacity))

    # -- admission ----------------------------------------------------

    def quota_bucket(self, name: str) -> RateBucket:
        b = self._quota.get(name)
        if b is None:
            ts = self.cfg.lookup(name)
            b = RateBucket(ts.quota_rps, ts.quota_burst)
            self._quota[name] = b
        return b

    def token_bucket(self, name: str) -> RateBucket:
        b = self._token_quota.get(name)
        if b is None:
            ts = self.cfg.lookup(name)
            b = RateBucket(ts.token_quota_per_s,
                           ts.token_quota_burst)
            self._token_quota[name] = b
        return b

    def admit(self, req, now: float) -> Optional[str]:
        """Quota verdict for one FRESH arrival: None admits, else
        the shed reason. Isolation off admits everything (the
        contrast mode); retries and hedges are internal traffic and
        are never re-metered — the quota charges demand, not
        recovery."""
        name = tenant_of(req)
        if not self.isolation:
            self.admitted[name] = self.admitted.get(name, 0) + 1
            return None
        if not self.quota_bucket(name).take(now):
            self.quota_shed[name] = (
                self.quota_shed.get(name, 0) + 1)
            return "tenant_quota"
        cost = float(len(req.prompt) + req.max_new)
        if not self.token_bucket(name).take(now, cost):
            self.token_shed[name] = (
                self.token_shed.get(name, 0) + 1)
            return "tenant_token_quota"
        self.admitted[name] = self.admitted.get(name, 0) + 1
        return None

    def note_kv_deferred(self, name: str) -> None:
        self.kv_deferred[name] = self.kv_deferred.get(name, 0) + 1

    # -- reporting ----------------------------------------------------

    def report(self) -> Dict[str, object]:
        tenants: Dict[str, object] = {}
        names = sorted(set(self.admitted) | set(self.quota_shed)
                       | set(self.token_shed) | set(self.kv_deferred)
                       | {t.name for t in self.cfg.tenants})
        for name in names:
            ts = self.cfg.lookup(name)
            row: Dict[str, object] = {
                "qos": ts.qos,
                "weight": ts.weight,
                "admitted": self.admitted.get(name, 0),
                "quota_shed": self.quota_shed.get(name, 0),
                "token_shed": self.token_shed.get(name, 0),
            }
            if name in self._quota:
                row["quota"] = self._quota[name].report()
            if name in self._token_quota:
                row["token_quota"] = (
                    self._token_quota[name].report())
            if name in self.kv_deferred:
                row["kv_deferred"] = self.kv_deferred[name]
            tenants[name] = row
        return {
            "isolation": self.isolation,
            "drr_quantum": self.drr_quantum,
            "tenants": tenants,
        }


# -- the heavy-tailed tenant workload ----------------------------------


def _zipf_cum(users: int, a: float) -> List[float]:
    """Cumulative Zipf(a) weights over user ranks 0..users-1 — the
    heavy tail (rank 0 is the hottest user)."""
    w = [(u + 1) ** -a for u in range(users)]
    total = sum(w)
    cum: List[float] = []
    acc = 0.0
    for x in w:
        acc += x
        cum.append(acc / total)
    return cum


def _user_cohort(seed: int, tenant: str, user: int,
                 prefix_len: int, vocab: int) -> tuple:
    """A (tenant, user)'s stable prefix cohort: group id and shared
    prompt prefix, from a crc32 sub-stream — same (seed, tenant,
    user), same cohort, independent of draw order."""
    sub = random.Random(zlib.crc32(
        f"tenant-prefix:{seed}:{tenant}:{user}".encode("utf-8")))
    group = sub.randrange(2 ** 31)
    prefix = tuple(sub.randrange(vocab)
                   for _ in range(max(1, prefix_len)))
    return group, prefix


def generate_tenant_trace(spec, seed: int) -> list:
    """The tenancy-on trace (``loadgen.generate_trace`` delegates
    here when ``spec.tenancy`` is set): Lewis thinning against the
    process's peak rate — the untenanted arrival algorithm — then
    each accepted arrival opens a session from a (tenant, user) pair
    drawn by share and Zipf rank. Session requests are think-time
    spaced, share the user's prefix cohort (at the spec's
    ``shared_prefix_frac``), and the merged trace is sorted by
    (arrival, draw order) with ids assigned in final order —
    byte-stable through save/load like every other trace."""
    from kind_tpu_sim.fleet.loadgen import (
        TraceRequest,
        _rate_at,
        _spec_rng,
    )

    tn: TenancyConfig = spec.tenancy
    rng = _spec_rng(spec, seed)
    if spec.process == "bursty":
        peak = spec.rps * max(1.0, spec.burst_factor)
    elif spec.process == "diurnal":
        peak = 2.0 * spec.rps
    else:
        peak = spec.rps
    share_total = sum(t.rps_share for t in tn.tenants)
    share_cum: List[float] = []
    acc = 0.0
    for t in tn.tenants:
        acc += t.rps_share / share_total
        share_cum.append(acc)
    zipf_cum = {t.name: _zipf_cum(t.users, t.zipf_a)
                for t in tn.tenants}
    cohorts: Dict[tuple, tuple] = {}
    entries: List[tuple] = []
    t_now = 0.0
    gen = 0
    while len(entries) < spec.n_requests:
        t_now += rng.expovariate(peak)
        if rng.random() * peak > _rate_at(spec, t_now):
            continue
        ts = tn.tenants[min(
            bisect.bisect_left(share_cum, rng.random()),
            len(tn.tenants) - 1)]
        user = bisect.bisect_left(zipf_cum[ts.name], rng.random())
        user = min(user, ts.users - 1)
        n_sess = rng.randint(*ts.session_len)
        for k in range(n_sess):
            at = round(t_now + k * ts.think_time_s, 6)
            p_len = rng.randint(*spec.prompt_len)
            grouped = (spec.shared_prefix_frac > 0
                       and rng.random() < spec.shared_prefix_frac)
            if grouped:
                key = (ts.name, user)
                if key not in cohorts:
                    cohorts[key] = _user_cohort(
                        seed, ts.name, user, spec.prefix_len,
                        spec.vocab)
                group, prefix = cohorts[key]
                body_len = max(1, p_len - len(prefix))
                prompt = prefix + tuple(
                    rng.randrange(spec.vocab)
                    for _ in range(body_len))
            else:
                group = -1
                prompt = tuple(rng.randrange(spec.vocab)
                               for _ in range(max(1, p_len)))
            entries.append((
                at, gen, prompt, rng.randint(*spec.max_new),
                rng.randrange(2 ** 31), group, ts.name, user))
            gen += 1
    entries.sort(key=lambda e: (e[0], e[1]))
    entries = entries[:spec.n_requests]
    out = []
    for i, (at, _gen, prompt, max_new, req_seed, group, tname,
            user) in enumerate(entries):
        out.append(TraceRequest(
            request_id=f"t{i:05d}",
            arrival_s=at,
            prompt=prompt,
            max_new=max_new,
            seed=req_seed,
            prefix_group=group,
            deadline_s=spec.deadline_s,
            tenant=tname,
            user_id=user,
        ))
    return out


# -- the noisy_neighbor / tenant_surge trace transforms ----------------


def tenant_surge_trace(spec, seed: int, t0: float, t1: float,
                       multiplier: float, tenant: str) -> list:
    """The ``noisy_neighbor`` / ``tenant_surge`` fault kinds'
    workload: the base tenant trace plus a step of extra arrivals
    from ONE tenant at ``(multiplier - 1) x`` its nominal rate,
    confined to ``[t0, t1)`` and drawn from a crc32 sub-seed (the
    ``surge_trace`` recipe) — same (spec, seed, window, multiplier,
    tenant), same surge, byte for byte. Surge ids are ``s``-prefixed
    so the merged trace stays id-unique."""
    from kind_tpu_sim.fleet.loadgen import generate_trace

    tn: TenancyConfig = spec.tenancy
    ts = tn.lookup(tenant)
    share = ts.rps_share / sum(t.rps_share for t in tn.tenants)
    extra_rps = spec.rps * share * max(0.0, multiplier - 1.0)
    n_extra = int(extra_rps * max(0.0, t1 - t0))
    merged = list(generate_trace(spec, seed))
    if n_extra > 0:
        sub_seed = zlib.crc32(repr(
            ("tenant-surge", seed, tenant, round(t0, 6),
             round(t1, 6), round(multiplier, 6))).encode("utf-8"))
        surge_spec = dataclasses.replace(
            spec, process="poisson", rps=extra_rps,
            n_requests=n_extra,
            tenancy=TenancyConfig(tenants=(ts,),
                                  isolation=tn.isolation,
                                  drr_quantum=tn.drr_quantum))
        for req in generate_trace(surge_spec, sub_seed):
            at = round(t0 + req.arrival_s, 6)
            if at >= t1:
                break
            merged.append(dataclasses.replace(
                req, request_id=f"s{req.request_id}",
                arrival_s=at))
    merged.sort(key=lambda r: (r.arrival_s, r.request_id))
    return merged
