"""Seeded open-loop workload generation on a virtual clock.

The fleet simulator's traffic source. Everything here is a pure
function of (spec, seed): the arrival process, the sampled prompt and
output lengths, the shared-prefix structure, and the per-request
sampling seeds are all drawn from one ``random.Random`` stream keyed
by the canonical argument repr (the ChaosSchedule recipe), so the same
seed yields BIT-IDENTICAL traffic — the determinism contract every
fleet report and chaos invariant rests on.

Open-loop means arrivals do not wait for completions (the
Poisson-arrival serving-benchmark shape): a saturated fleet keeps
receiving requests, which is exactly the regime SLO attainment,
shedding, and autoscaling are about. Three arrival processes:

* ``poisson``  — exponential inter-arrivals at ``rps``.
* ``bursty``   — on/off modulation: bursts of ``burst_factor * rps``
                 alternating with quiet valleys (mean rate ~ rps).
* ``diurnal``  — a sinusoidal rate profile over the trace duration
                 (the compressed day/night cycle autoscalers chase).

Traces round-trip through JSON lines (:func:`save_trace` /
:func:`load_trace`) so a generated workload can be replayed against a
different policy/replica count — same requests, different fleet.
"""

from __future__ import annotations

import dataclasses
import json
import math
import random
import zlib
from typing import TYPE_CHECKING, List, Optional, Sequence

from kind_tpu_sim.analysis import knobs

if TYPE_CHECKING:  # import cycle: tenancy builds TraceRequests
    from kind_tpu_sim.fleet.tenancy import TenancyConfig

FLEET_SEED_ENV = knobs.FLEET_SEED


def resolve_seed(seed: Optional[int] = None) -> int:
    """Explicit seed > env (KIND_TPU_SIM_FLEET_SEED) > 0."""
    if seed is not None:
        return int(seed)
    return int(knobs.get(FLEET_SEED_ENV))


class VirtualClock:
    """The fleet's notion of time: starts at 0.0, only moves when the
    simulator advances it. Every latency the fleet reports (TTFT,
    TPOT, e2e, deadline expiry, autoscaler warm-up) is measured on
    THIS clock, never the wall — which is what makes two runs of the
    same seed byte-identical regardless of host load."""

    def __init__(self, start: float = 0.0):
        self._now = float(start)

    def now(self) -> float:
        return self._now

    def advance(self, dt: float) -> float:
        if dt < 0:
            raise ValueError(f"virtual time cannot rewind (dt={dt})")
        self._now += dt
        return self._now


@dataclasses.dataclass(frozen=True)
class TraceRequest:
    """One generated request: ``arrival_s`` is virtual time;
    ``prefix_group`` >= 0 marks membership in a shared-prompt-prefix
    cohort (the prefix-affinity router's routing key and the
    PrefixCache's hit population); ``deadline_s`` is the per-request
    e2e budget relative to arrival (None = no deadline)."""

    request_id: str
    arrival_s: float
    prompt: tuple
    max_new: int
    seed: int
    prefix_group: int = -1
    deadline_s: Optional[float] = None
    # multi-tenancy (docs/TENANCY.md): the declared tenant and the
    # user rank inside it; empty/-1 on every untenanted trace
    tenant: str = ""
    user_id: int = -1
    # model zoo (docs/ZOO.md): the named model this request targets;
    # empty on every unzooed trace
    model: str = ""

    def as_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["prompt"] = list(self.prompt)
        # default-valued tenancy/zoo fields stay OFF the wire so
        # every untenanted, unzooed trace file and replay stays
        # byte-identical
        if not self.tenant:
            d.pop("tenant")
        if self.user_id < 0:
            d.pop("user_id")
        if not self.model:
            d.pop("model")
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "TraceRequest":
        d = dict(d)
        d["prompt"] = tuple(d["prompt"])
        return cls(**d)


@dataclasses.dataclass(frozen=True)
class WorkloadSpec:
    """Knobs of one generated workload. ``process`` is the arrival
    model; lengths are drawn uniform in [lo, hi] (closed); a
    ``shared_prefix_frac`` of requests get a group-common prompt
    prefix of ``prefix_len`` tokens drawn per group."""

    process: str = "poisson"        # poisson | bursty | diurnal
    rps: float = 50.0               # mean arrival rate (requests/s)
    n_requests: int = 100
    prompt_len: Sequence[int] = (4, 24)
    max_new: Sequence[int] = (4, 16)
    vocab: int = 64
    shared_prefix_frac: float = 0.0  # fraction of requests in groups
    prefix_groups: int = 4
    prefix_len: int = 8
    deadline_s: Optional[float] = None  # uniform per-request budget
    burst_factor: float = 4.0       # bursty: peak rate multiplier
    burst_period_s: float = 2.0     # bursty: one on+off cycle
    diurnal_period_s: float = 20.0  # diurnal: one day (compressed)
    # phase offset of the modulated processes (virtual s): a zone at
    # phase_s = period/3 peaks a third of a day later — the
    # peak-follows-the-sun lever the globe layer staggers its
    # per-zone diurnal demand with (docs/GLOBE.md)
    phase_s: float = 0.0
    # multi-tenant population (docs/TENANCY.md): when set,
    # generation delegates to tenancy.generate_tenant_trace — the
    # heavy-tailed user model; None keeps the anonymous streams
    tenancy: Optional["TenancyConfig"] = None
    # model zoo (docs/ZOO.md): when set, every generated request is
    # stamped with a model name drawn from the zoo's per-tenant
    # mixes on a FRESH crc32 stream — the base trace (arrivals,
    # prompts, seeds) comes off the unchanged spec stream, so every
    # zoo-off trace and replay stays byte-identical
    zoo: Optional[object] = None

    PROCESSES = ("poisson", "bursty", "diurnal")


def _spec_rng(spec: WorkloadSpec, seed: int) -> random.Random:
    sig = (seed, spec.process, spec.rps, spec.n_requests,
           tuple(spec.prompt_len), tuple(spec.max_new),
           spec.vocab, spec.shared_prefix_frac,
           spec.prefix_groups, spec.prefix_len, spec.deadline_s,
           spec.burst_factor, spec.burst_period_s,
           spec.diurnal_period_s)
    # phase_s joins the identity key only when set: every phase-0
    # spec keeps its pre-globe stream (seed compatibility is the
    # byte-identity contract every scenario report rests on)
    if spec.phase_s:
        sig = sig + (spec.phase_s,)
    # tenancy joins the same way: untenanted specs keep their
    # streams, and the key carries only the traffic-shaping tenant
    # fields (quota/weight changes compare on identical traces)
    if spec.tenancy is not None:
        sig = sig + (spec.tenancy.signature(),)
    return random.Random(zlib.crc32(repr(sig).encode("utf-8")))


def _rate_at(spec: WorkloadSpec, t: float) -> float:
    """Instantaneous arrival rate of the modulated processes (the
    thinning envelope); constant for poisson."""
    if spec.process == "poisson":
        return spec.rps
    if spec.process == "bursty":
        # on/off with duty cycle 1/burst_factor: bursts run at
        # burst_factor * rps, valleys are silent, mean is EXACTLY rps
        phase = (((t + spec.phase_s) % spec.burst_period_s)
                 / spec.burst_period_s)
        duty = 1.0 / max(1.0, spec.burst_factor)
        return (spec.rps * max(1.0, spec.burst_factor)
                if phase < duty else 0.0)
    if spec.process == "diurnal":
        # raised cosine: peaks at mid-period, valleys at the edges,
        # mean exactly rps; phase_s slides the peak (two zones a
        # half-period apart peak in antiphase)
        phase = (((t + spec.phase_s) % spec.diurnal_period_s)
                 / spec.diurnal_period_s)
        return spec.rps * (1.0 - math.cos(2 * math.pi * phase))
    raise ValueError(
        f"unknown arrival process {spec.process!r}; known: "
        f"{', '.join(WorkloadSpec.PROCESSES)}")


def generate_trace(spec: WorkloadSpec,
                   seed: Optional[int] = None) -> List[TraceRequest]:
    """The seeded trace: ``n_requests`` arrivals via Lewis thinning
    against the process's peak rate (exact for poisson, and the one
    algorithm that serves all three processes from one stream), each
    with sampled prompt/output lengths, an explicit per-request
    sampling seed (replayable through a real engine), and optional
    shared-prefix group membership."""
    if spec.process not in WorkloadSpec.PROCESSES:
        raise ValueError(
            f"unknown arrival process {spec.process!r}; known: "
            f"{', '.join(WorkloadSpec.PROCESSES)}")
    if spec.rps <= 0:
        raise ValueError(f"rps must be > 0 (got {spec.rps})")
    seed = resolve_seed(seed)
    if spec.tenancy is not None:
        # the multi-tenant population (docs/TENANCY.md); lazy import
        # breaks the loadgen <-> tenancy cycle
        from kind_tpu_sim.fleet.tenancy import generate_tenant_trace

        return _stamp_zoo(spec, generate_tenant_trace(spec, seed),
                          seed)
    rng = _spec_rng(spec, seed)
    # thinning envelope: each process's peak instantaneous rate
    if spec.process == "bursty":
        peak = spec.rps * max(1.0, spec.burst_factor)
    elif spec.process == "diurnal":
        peak = 2.0 * spec.rps  # raised-cosine max
    else:
        peak = spec.rps
    group_prefixes = [
        tuple(rng.randrange(spec.vocab)
              for _ in range(spec.prefix_len))
        for _ in range(max(1, spec.prefix_groups))]
    out: List[TraceRequest] = []
    t = 0.0
    i = 0
    while len(out) < spec.n_requests:
        t += rng.expovariate(peak)
        if rng.random() * peak > _rate_at(spec, t):
            continue  # thinned: outside this instant's rate envelope
        p_len = rng.randint(*spec.prompt_len)
        grouped = (spec.shared_prefix_frac > 0
                   and rng.random() < spec.shared_prefix_frac)
        group = (rng.randrange(max(1, spec.prefix_groups))
                 if grouped else -1)
        if grouped:
            prefix = group_prefixes[group]
            body_len = max(1, p_len - len(prefix))
            prompt = prefix + tuple(
                rng.randrange(spec.vocab) for _ in range(body_len))
        else:
            prompt = tuple(rng.randrange(spec.vocab)
                           for _ in range(max(1, p_len)))
        out.append(TraceRequest(
            request_id=f"f{i:05d}",
            arrival_s=round(t, 6),
            prompt=prompt,
            max_new=rng.randint(*spec.max_new),
            seed=rng.randrange(2 ** 31),
            prefix_group=group,
            deadline_s=spec.deadline_s,
        ))
        i += 1
    return _stamp_zoo(spec, out, seed)


def _stamp_zoo(spec: WorkloadSpec, trace: List[TraceRequest],
               seed: int) -> List[TraceRequest]:
    """Stamp a model on every request when the spec declares a zoo
    (docs/ZOO.md). The draws come off a fresh crc32 sub-stream keyed
    by the zoo's mix signature — the base trace's rng stream is
    never touched, so zoo-off traces stay byte-identical."""
    if spec.zoo is None:
        return trace
    from kind_tpu_sim.fleet.zoo import stamp_models

    return stamp_models(spec.zoo, trace, seed)


def save_trace(path: str, trace: Sequence[TraceRequest]) -> None:
    """One JSON object per line, keys sorted — a byte-stable artifact
    (diffable across runs of the same seed)."""
    with open(path, "w", encoding="utf-8") as fh:
        for req in trace:
            fh.write(json.dumps(req.as_dict(), sort_keys=True))
            fh.write("\n")


def load_trace(path: str) -> List[TraceRequest]:
    out = []
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if line:
                out.append(TraceRequest.from_dict(json.loads(line)))
    return out
