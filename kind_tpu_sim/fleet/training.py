"""Training as a first-class fleet tenant (docs/TRAINING.md).

The tenant class the scheduler's strict-priority preemption, defrag,
and chaos machinery were built for, finally running *inside* the sim
(ROADMAP item 5): a **training gang** is a long-running scheduler-
placed workload with a throughput SLO (work units per virtual second,
time-to-completion) co-scheduled UNDER serving — serving replicas run
at priority 10, training gangs default to the batch tier's -10
(``pods/tpu-batch-train-job.yaml``), so a serving scale-up evicts
training first and training only ever scavenges what serving leaves.

Three pieces:

* **Step model** — a gang steps in CLOSED FORM on the event core:
  step time = perfectly-scaling compute share / chips + the ring
  all-reduce of the gradient exchange over the gang's ICI block
  (:func:`kind_tpu_sim.parallel.collectives.ring_allreduce_s`, the
  same slowest-link model the gray-failure math uses), sized from a
  logical GSPMD mesh (:func:`gang_mesh` — the NamedSharding
  ``(data, model)`` mesh shape of SNIPPETS [1]/[3], derived from
  :mod:`kind_tpu_sim.topology` exactly as `parallel/mesh.py` derives
  device meshes). Advancing a segment in one call or a hundred
  produces identical floats — the partition invariance the event
  core (docs/PERFORMANCE.md) rests on. The non-LLM tenant kind
  (``ising`` — Monte-Carlo Ising sweeps, PAPERS.md 1903.11714) is
  all-throughput/no-latency and nearly collective-free, so it ships
  sub-host chip-granular gangs that stress binpack/defrag in ways a
  latency tenant cannot.

* **Checkpoint economics** — the cadence knob trades checkpoint
  write cost against expected lost-step work under the measured
  preemption rate (:func:`optimal_cadence_steps` is the Young-Daly
  optimum; :func:`expected_overhead` prices any cadence). Graceful
  preemption (``replica_preempt`` displacement, ``node_drain``,
  ``node_fail``, zone loss, spot reclaim) follows
  ``models/checkpoint.PreemptionGuard`` semantics: checkpoint at the
  current (last completed) step -> evict -> reschedule -> resume
  bit-identical, so ZERO counted steps are ever lost; a HARD kill
  (``train_kill`` — no 30s grace) rolls back to the last cadence
  checkpoint and re-runs the gap, which is exactly the work the
  cadence is priced against. The **progress ledger** records every
  run segment, checkpoint, rollback, and resize;
  :func:`verify_ledger` machine-checks zero-lost/zero-duplicated
  against it.

* **Elasticity** — an elastic gang grows onto scavenged capacity
  (free inventory, or a spot grant from the globe planner,
  docs/GLOBE.md) by a checkpointed repartition: checkpoint -> evict
  -> resubmit at the doubled topology -> resume with a modeled
  restart cost; on reclaim it shrinks back toward its base shape —
  shrink-never-abort, the gang always finishes.

Determinism: no wall clock, no entropy — every number is a pure
function of (config, virtual time); the loss trajectory itself is a
seeded closed form (:meth:`TrainingGang.loss_at`) so resume
bit-identity is testable without jax.
"""

from __future__ import annotations

import dataclasses
import math
import zlib
from typing import Dict, List, Optional, Tuple

from kind_tpu_sim import metrics
from kind_tpu_sim import topology as topo
from kind_tpu_sim.analysis import knobs
from kind_tpu_sim.parallel import collectives

TRAIN_KINDS = ("llm", "ising")

CKPT_EVERY_ENV = knobs.TRAIN_CKPT_EVERY
CKPT_WRITE_ENV = knobs.TRAIN_CKPT_WRITE_S
RESTART_ENV = knobs.TRAIN_RESTART_S
MTBF_ENV = knobs.TRAIN_MTBF_S
ELASTIC_ENV = knobs.TRAIN_ELASTIC

# scheduler gang-name prefix: keeps the training namespace disjoint
# from the serving fleet's "replica-N" gangs
GANG_PREFIX = "train-"


def resolve_ckpt_write_s(value: Optional[float] = None) -> float:
    """Explicit value > env (KIND_TPU_SIM_TRAIN_CKPT_WRITE_S) >
    0.05."""
    if value is not None:
        return float(value)
    return float(knobs.get(CKPT_WRITE_ENV))


def resolve_restart_s(value: Optional[float] = None) -> float:
    """Explicit value > env (KIND_TPU_SIM_TRAIN_RESTART_S) > 0.2."""
    if value is not None:
        return float(value)
    return float(knobs.get(RESTART_ENV))


def resolve_mtbf_s(value: Optional[float] = None) -> float:
    """Explicit value > env (KIND_TPU_SIM_TRAIN_MTBF_S) > 60."""
    if value is not None:
        return float(value)
    return float(knobs.get(MTBF_ENV))


def resolve_elastic(value: Optional[bool] = None) -> bool:
    """Explicit value > env (KIND_TPU_SIM_TRAIN_ELASTIC) > on."""
    if value is not None:
        return bool(value)
    return bool(knobs.get(ELASTIC_ENV))


# -- the GSPMD mesh + step model ---------------------------------------


def gang_mesh(accelerator: str, topology_str: str,
              kind: str = "llm") -> Dict[str, int]:
    """Logical GSPMD mesh for a gang's ICI block — the NamedSharding
    mesh shape (SNIPPETS [1]/[3]) the gang's train step would run
    under, derived from :class:`~kind_tpu_sim.topology.SliceTopology`
    the same way ``parallel/mesh.py`` derives device meshes. LLM
    gangs shard ``(data, model)`` = (hosts, chips-per-host): data-
    parallel across hosts (the gradient ring crosses ICI), model-
    parallel within a host. Ising sweeps are embarrassingly parallel
    — one ``batch`` axis over every chip, no meaningful collective.
    """
    if kind not in TRAIN_KINDS:
        raise ValueError(
            f"unknown training kind {kind!r}; known: "
            f"{', '.join(TRAIN_KINDS)}")
    s = topo.make_slice(accelerator, topology_str)
    if kind == "ising":
        return {"batch": s.num_chips}
    return {"data": s.num_hosts, "model": s.chips_per_host}


@dataclasses.dataclass(frozen=True)
class TrainingGangConfig:
    """One training tenant. ``topology`` is the base ICI block the
    gang is submitted at; an elastic gang may grow up to
    ``max_topology`` (doubling the last dimension per step) and
    shrinks back toward the base on reclaim. ``step_compute_chip_s``
    is the perfectly-scaling per-step compute on ONE chip;
    ``allreduce_bytes`` is the per-step gradient exchange the ring
    model prices over the gang's hosts. ``work_per_step`` /
    ``work_unit`` are the throughput SLO's reporting units (tokens
    for LLM, lattice sweeps for Ising)."""

    name: str
    kind: str = "llm"
    accelerator: str = topo.DEFAULT_ACCELERATOR
    topology: str = "4x4"
    priority: int = -10
    arrival_s: float = 0.0
    total_steps: int = 120
    step_compute_chip_s: float = 0.4
    allreduce_bytes: float = 100e6
    work_per_step: float = 65536.0
    work_unit: str = "tok"
    checkpoint_every: Optional[int] = None  # None -> knob (0=auto)
    checkpoint_write_s: Optional[float] = None
    restart_s: Optional[float] = None
    elastic: bool = False
    max_topology: Optional[str] = None
    loss_seed: int = 0

    def __post_init__(self) -> None:
        if self.kind not in TRAIN_KINDS:
            raise ValueError(
                f"unknown training kind {self.kind!r}; known: "
                f"{', '.join(TRAIN_KINDS)}")
        topo.make_slice(self.accelerator, self.topology)
        if self.max_topology is not None:
            topo.make_slice(self.accelerator, self.max_topology)
        if self.total_steps <= 0:
            raise ValueError("total_steps must be > 0")

    def as_dict(self) -> dict:
        out = {
            "name": self.name,
            "kind": self.kind,
            "accelerator": self.accelerator,
            "topology": self.topology,
            "priority": self.priority,
            "arrival_s": round(self.arrival_s, 6),
            "total_steps": self.total_steps,
            "step_compute_chip_s": self.step_compute_chip_s,
            "allreduce_bytes": self.allreduce_bytes,
            "work_per_step": self.work_per_step,
            "work_unit": self.work_unit,
            "elastic": self.elastic,
            "loss_seed": self.loss_seed,
        }
        if self.max_topology is not None:
            out["max_topology"] = self.max_topology
        if self.checkpoint_every is not None:
            out["checkpoint_every"] = self.checkpoint_every
        if self.checkpoint_write_s is not None:
            out["checkpoint_write_s"] = self.checkpoint_write_s
        if self.restart_s is not None:
            out["restart_s"] = self.restart_s
        return out


def ising_gang(name: str, **overrides) -> TrainingGangConfig:
    """An all-throughput Monte-Carlo Ising sweep tenant (PAPERS.md
    1903.11714): sub-host chip-granular (binpack fodder — it fits in
    fragments no gang block can use), essentially collective-free (a
    per-sweep scalar energy reduce), progress counted in lattice
    sweeps."""
    base = dict(kind="ising", topology="2x2",
                step_compute_chip_s=0.08, allreduce_bytes=8.0,
                work_per_step=1.0, work_unit="sweep")
    base.update(overrides)
    return TrainingGangConfig(name=name, **base)


def step_time_s(cfg: TrainingGangConfig, topology_str: str,
                link_factor: float = 1.0) -> float:
    """Closed-form per-step time of ``cfg`` on an ICI block of shape
    ``topology_str`` whose slowest link runs at ``link_factor`` of
    nominal: compute scales perfectly with chips (fixed global
    batch), the gradient ring runs over the block's hosts at the
    slowest link's pace. Single-host gangs pay no ring (intra-host
    bandwidth is not the modeled bottleneck) — which is exactly why
    the Ising tenant's placement is ICI-indifferent."""
    s = topo.make_slice(cfg.accelerator, topology_str)
    compute = cfg.step_compute_chip_s / s.num_chips
    ring = collectives.ring_allreduce_s(
        cfg.allreduce_bytes, s.num_hosts,
        link_factors=[link_factor], tier="ici")
    return compute + ring


def grow_topology(accelerator: str,
                  topology_str: str) -> Optional[str]:
    """The elastic ladder's next rung: double the last topology
    dimension (4x4 -> 4x8 -> 4x16). None when the doubled shape is
    not a valid slice of this accelerator."""
    dims = topo.parse_topology(topology_str)
    grown = dims[:-1] + (dims[-1] * 2,)
    try:
        topo.make_slice(accelerator, topo.format_topology(grown))
    except ValueError:
        return None
    return topo.format_topology(grown)


def shrink_topology(accelerator: str, topology_str: str,
                    floor: str) -> Optional[str]:
    """The ladder's previous rung (halve the last dimension), never
    below ``floor`` — shrink-never-abort means the base shape is the
    hard minimum."""
    dims = topo.parse_topology(topology_str)
    if dims[-1] % 2 != 0:
        return None
    shrunk = dims[:-1] + (dims[-1] // 2,)
    shrunk_str = topo.format_topology(shrunk)
    floor_chips = topo.make_slice(accelerator, floor).num_chips
    if topo.make_slice(accelerator, shrunk_str).num_chips \
            < floor_chips:
        return None
    return shrunk_str


# -- checkpoint economics ----------------------------------------------


def optimal_cadence_steps(step_s: float, ckpt_write_s: float,
                          mtbf_s: float) -> int:
    """The Young-Daly checkpoint interval, in steps: sqrt(2 * write
    cost * MTBF) of work between checkpoints minimizes (write
    overhead + expected re-run after a hard kill). Floored at one
    step."""
    if step_s <= 0:
        raise ValueError(f"step_s must be > 0; got {step_s}")
    interval_s = math.sqrt(2.0 * max(ckpt_write_s, 0.0)
                           * max(mtbf_s, 0.0))
    return max(1, int(round(interval_s / step_s)))


def expected_overhead(step_s: float, cadence_steps: int,
                      ckpt_write_s: float,
                      mtbf_s: float) -> Dict[str, float]:
    """The economics of one cadence choice: ``write_frac`` is time
    spent writing checkpoints per unit of work, ``lost_frac`` the
    expected re-run fraction under HARD kills at the given MTBF
    (half an interval plus one restart's worth of re-derivation on
    average), ``total_frac`` their sum — the number the cadence knob
    minimizes. Graceful preemptions (the PreemptionGuard path) cost
    restarts but never re-runs, so they are priced separately by the
    simulated runs themselves."""
    interval_s = cadence_steps * step_s
    write_frac = ckpt_write_s / (interval_s + ckpt_write_s)
    lost_frac = ((interval_s / 2.0 + ckpt_write_s)
                 / max(mtbf_s, 1e-9))
    return {
        "interval_s": round(interval_s, 6),
        "write_frac": round(write_frac, 6),
        "lost_frac": round(lost_frac, 6),
        "total_frac": round(write_frac + lost_frac, 6),
    }


# -- the ledger --------------------------------------------------------


def verify_ledger(ledger: List[dict],
                  total_steps: int) -> Dict[str, object]:
    """Machine-check the zero-lost / zero-duplicated contract
    against the gang's own progress ledger: replaying the segment /
    checkpoint / rollback records in order, every ``run`` segment
    must start exactly where committed progress stood (no gap =
    nothing silently lost, no overlap = nothing double-counted; an
    overlap is legal only as the re-run a ``rollback`` record
    explicitly opened). Returns unique/lost/re-run step counts and
    the violation list (empty = the contract held)."""
    committed = 0
    high_water = 0
    lost = 0
    rerun = 0
    violations: List[str] = []
    for rec in ledger:
        kind = rec.get("kind")
        if kind == "run":
            if rec["from_step"] != committed:
                violations.append(
                    f"segment at t0={rec['t0']} starts at step "
                    f"{rec['from_step']}, committed progress was "
                    f"{committed}")
            if rec["to_step"] < rec["from_step"]:
                violations.append(
                    f"segment at t0={rec['t0']} runs backwards")
            rerun += max(0, min(high_water, rec["to_step"])
                         - rec["from_step"])
            committed = rec["to_step"]
            high_water = max(high_water, committed)
        elif kind == "rollback":
            if rec["from_step"] != committed:
                violations.append(
                    f"rollback at {rec['at_s']} from step "
                    f"{rec['from_step']}, committed was {committed}")
            lost += rec["from_step"] - rec["to_step"]
            committed = rec["to_step"]
    if committed > total_steps:
        violations.append(
            f"committed {committed} > total {total_steps}")
    return {
        "ok": not violations,
        "unique_steps": committed,
        "lost_steps": lost,
        "rerun_steps": rerun,
        "violations": violations,
    }


# -- one gang ----------------------------------------------------------


class TrainingGang:
    """Runtime state of one gang: a closed-form segment timeline.

    Within one **segment** (one binding at one shape on one ICI
    domain) step completion times are an affine function of the step
    index — ``f(n) = n * step_s + (cadence checkpoints crossed) *
    write_s`` from the segment origin — so progress at any instant
    is computed by inverting ``f``, never by accumulating per-tick
    remainders (partition invariance). Every binding, checkpoint,
    rollback, and resize appends to the progress ledger."""

    def __init__(self, cfg: TrainingGangConfig, *,
                 ckpt_every: int, ckpt_write_s: float,
                 restart_s: float, elastic: bool):
        self.cfg = cfg
        self.ckpt_every = max(1, int(ckpt_every))
        self.ckpt_write_s = float(ckpt_write_s)
        self.restart_s = float(restart_s)
        self.elastic = bool(elastic and cfg.elastic)
        self.topology = cfg.topology
        self.state = "waiting"  # waiting|pending|running|done
        self.steps_done = 0
        self.high_water = 0
        self.last_ckpt_step = 0
        self.step_s: Optional[float] = None
        self.seg_t0: Optional[float] = None
        self.seg_step0 = 0
        self.done_s: Optional[float] = None
        self.first_bound_s: Optional[float] = None
        self.ledger: List[dict] = []
        self.ckpt_writes = 0
        self.ckpt_time_s = 0.0
        self.restart_time_s = 0.0
        self.evictions = 0
        self.migrations = 0
        self.grows = 0
        self.shrinks = 0
        self.lost_steps = 0
        # one growth rung per outstanding spot grant
        self.spot_rungs = 0
        # -- silent data corruption (docs/SDC.md): chip index ->
        # corrupt fraction for every live defective chip in the
        # gang; a defect PERSISTS until bisection names the chip
        # and quarantine pulls it
        self.sdc_chips: Dict[int, float] = {}
        # first step index whose loss the live defects perturb —
        # the closed-form detection event (None = clean horizon)
        self._sdc_spike_step: Optional[int] = None
        # named culprits (chip, detection step, bisection rounds)
        self.sdc_culprits: List[dict] = []
        # verdicts awaiting the fleet driver's quarantine drain
        self.sdc_verdicts_out: List[dict] = []

    # -- the closed-form timeline ---------------------------------

    def _ckpts_through(self, a: int, b: int) -> int:
        """Cadence checkpoints written after steps in (a, b] —
        mirrors ``train_with_checkpointing``'s ``done % every == 0``
        rule (the final step's own write is priced separately)."""
        every = self.ckpt_every
        return b // every - a // every

    def _f(self, n: int) -> float:
        """Virtual seconds from the segment origin to completion of
        the segment's n-th step: pure in n (a multiply each), never
        an accumulation — one call or a hundred land on identical
        floats."""
        if n <= 0:
            return 0.0
        writes = self._ckpts_through(self.seg_step0,
                                     self.seg_step0 + n - 1)
        return n * self.step_s + writes * self.ckpt_write_s

    def _steps_at(self, now: float) -> int:
        """Completed segment steps by ``now`` (clamped to the
        remaining work): largest n with f(n) <= elapsed, by binary
        search over the monotone closed form."""
        if self.seg_t0 is None or now < self.seg_t0:
            return 0
        elapsed = now - self.seg_t0
        lo, hi = 0, self.cfg.total_steps - self.seg_step0
        while lo < hi:
            mid = (lo + hi + 1) // 2
            if self._f(mid) <= elapsed:
                lo = mid
            else:
                hi = mid - 1
        return lo

    def completion_s(self) -> Optional[float]:
        """The instant this segment would finish the gang (last step
        plus its final checkpoint write) — the event the core must
        step a boundary for. None unless running."""
        if self.state != "running":
            return None
        rem = self.cfg.total_steps - self.seg_step0
        return self.seg_t0 + self._f(rem) + self.ckpt_write_s

    def loss_at(self, step: int) -> float:
        """The gang's deterministic loss trajectory — a pure
        function of (loss_seed, step), which is exactly what makes
        resume bit-identity checkable: re-running a step after a
        resume MUST produce the identical float."""
        noise = zlib.crc32(
            f"{self.cfg.name}:{self.cfg.loss_seed}:{step}"
            .encode("utf-8")) / 2.0 ** 32
        loss = 4.0 / (1.0 + 0.05 * step) + 0.01 * noise
        if self._sdc_corrupts(step):
            # a defective chip perturbed this step's gradient: the
            # spike (+1.0 over a <=0.01 noise band) is what the
            # closed-form checker detects (docs/SDC.md)
            loss += 1.0
        return loss

    # -- silent data corruption (docs/SDC.md) ----------------------

    def _sdc_corrupts(self, step: int) -> bool:
        """Whether any LIVE defective chip corrupts ``step`` — a
        pure function of (gang name, chip, step, loss_seed, frac),
        so re-running the step during bisection reproduces the
        identical verdict."""
        for chip in sorted(self.sdc_chips):
            frac = self.sdc_chips[chip]
            draw = zlib.crc32(
                f"sdc:{self.cfg.name}:{chip}:{step}:"
                f"{self.cfg.loss_seed}".encode("utf-8")) / 2.0 ** 32
            if draw < frac:
                return True
        return False

    def _recompute_spike(self, from_step: int) -> None:
        """First corrupted step at or after ``from_step`` (bounded
        by total_steps) — the detection horizon advance() clamps
        segment progress against."""
        self._sdc_spike_step = None
        if not self.sdc_chips:
            return
        for step in range(max(1, from_step),
                          self.cfg.total_steps + 1):
            if self._sdc_corrupts(step):
                self._sdc_spike_step = step
                return

    def seed_defect(self, chip: int, frac: float,
                    now: float) -> None:
        """Chaos seeded a defective chip: from ``now`` on, a
        ``frac`` share of this gang's steps compute a silently wrong
        gradient. Progress through ``now`` commits clean first —
        the defect cannot retroactively corrupt finished work."""
        self.advance(now)
        if self.state == "done":
            return
        self.sdc_chips[int(chip)] = max(0.0, min(1.0, float(frac)))
        self._recompute_spike(self.steps_done + 1)

    def next_event_s(self) -> Optional[float]:
        """The segment's next boundary-condition instant for the
        event core: the SDC detection point when a spike is ahead
        (the loss checker fires there, docs/SDC.md), else the
        ordinary segment completion."""
        if self.state != "running":
            return None
        if self._sdc_spike_step is not None:
            return self.seg_t0 + self._f(
                self._sdc_spike_step - self.seg_step0)
        return self.completion_s()

    def _run_bisection(self, detect_step: int,
                       ts: float) -> Tuple[int, int, float]:
        """Deterministic culprit bisection: binary-search the gang's
        chip range by re-running the suspect segment (the rolled-
        back steps) on the candidate half-gang — the spike
        reproduces iff the defective chip is in the half, because
        :meth:`_sdc_corrupts` is a pure function of (chip, step).
        Every round is priced as REAL chip-seconds in the ledger
        (``bisect`` records). Returns (culprit chip, rounds, total
        re-run virtual seconds) — rounds <= ceil(log2(chips))."""
        chips = topo.make_slice(self.cfg.accelerator,
                                self.topology).num_chips
        culprit = min(self.sdc_chips)
        rerun = max(1, detect_step - self.last_ckpt_step)
        round_s = rerun * self.step_s
        lo, hi = 0, chips
        rounds = 0
        t = ts
        while hi - lo > 1:
            mid = (lo + hi) // 2
            hit = lo <= culprit < mid
            rounds += 1
            t += round_s
            self.ledger.append({
                "kind": "bisect", "round": rounds,
                "chips_lo": lo, "chips_hi": mid, "hit": hit,
                "steps": rerun,
                "chip_s": round(round_s * (mid - lo), 6),
                "at_s": round(t, 6),
            })
            metrics.integrity_board().incr("bisection_steps")
            if hit:
                hi = mid
            else:
                lo = mid
        return culprit, rounds, t - ts

    def _sdc_detect(self, spike_step: int, ts: float) -> None:
        """The loss checker fired at ``spike_step``'s completion:
        roll back to the last cadence checkpoint (PreemptionGuard
        semantics — the corrupted step itself never committed),
        bisect to the culprit chip, hand the verdict to the fleet
        driver's quarantine drain, and resume from the checkpoint
        with the defect retired."""
        self._close_segment(ts)
        lost = self.steps_done - self.last_ckpt_step
        if lost:
            self.ledger.append({
                "kind": "rollback",
                "from_step": self.steps_done,
                "to_step": self.last_ckpt_step,
                "at_s": round(ts, 6),
                "lost_steps": lost,
                "cause": "sdc",
            })
            self.lost_steps += lost
            self.steps_done = self.last_ckpt_step
            metrics.integrity_board().incr("steps_rolled_back",
                                           lost)
        metrics.integrity_board().incr("sdc_detections")
        culprit, rounds, bisect_s = self._run_bisection(
            spike_step, ts)
        frac = self.sdc_chips.pop(culprit)
        record = {
            "chip": culprit,
            "corrupt_frac": round(frac, 6),
            "detected_step": spike_step,
            "detected_at_s": round(ts, 6),
            "bisection_rounds": rounds,
            "lost_steps": lost,
        }
        self.sdc_culprits.append(record)
        self.sdc_verdicts_out.append(dict(record))
        resume = ts + bisect_s + self.restart_s
        self.restart_time_s += self.restart_s
        self.seg_t0 = resume
        self.seg_step0 = self.steps_done
        self.ledger.append({
            "kind": "sdc", "step": spike_step,
            "culprit_chip": culprit, "rounds": rounds,
            "at_s": round(ts, 6), "resume_s": round(resume, 6),
        })
        metrics.train_board().incr("sdc_detections")
        metrics.recovery_log().record(
            "train_sdc_detected", gang=self.cfg.name,
            step=spike_step, chip=culprit, rounds=rounds,
            at_s=round(ts, 6))
        self._recompute_spike(self.steps_done + 1)

    # -- lifecycle -------------------------------------------------

    def advance(self, now: float) -> None:
        """Commit progress through ``now``: closed-form step count,
        cadence checkpoint records for every boundary crossed, and
        the done transition (with its final checkpoint) when the
        last step lands. When a defective chip's loss spike lies in
        the window, the clean prefix commits first, the detection /
        rollback / bisection sequence runs at its closed-form
        instants, and the loop resumes committing in the reopened
        segment — one call or a hundred land on identical ledgers
        (partition invariance, docs/SDC.md)."""
        while self.state == "running":
            spike = self._sdc_spike_step
            if spike is None or self.seg_t0 is None:
                break
            rel = spike - self.seg_step0
            ts = self.seg_t0 + self._f(rel)
            if now < ts:
                break
            # commit exactly the clean prefix (through spike-1 —
            # the corrupted step itself must never commit), then
            # detect at the spike step's completion instant
            self._advance_core(self.seg_t0 + self._f(rel - 1))
            self._sdc_detect(spike, ts)
        self._advance_core(now)

    def _advance_core(self, now: float) -> None:
        if self.state != "running":
            return
        n = self._steps_at(now)
        new_done = self.seg_step0 + n
        if new_done > self.steps_done:
            every = self.ckpt_every
            first = (self.steps_done // every + 1) * every
            for c in range(first, new_done + 1, every):
                self.ledger.append({
                    "kind": "ckpt", "step": c,
                    "at_s": round(self.seg_t0
                                  + self._f(c - self.seg_step0), 6),
                    "write_s": self.ckpt_write_s,
                })
                self.ckpt_writes += 1
                self.ckpt_time_s += self.ckpt_write_s
                self.last_ckpt_step = c
            self.steps_done = new_done
            self.high_water = max(self.high_water, new_done)
        done_s = self.completion_s()
        if (self.steps_done >= self.cfg.total_steps
                and now >= done_s):
            self._close_segment(done_s)
            if self.last_ckpt_step < self.cfg.total_steps:
                self.ledger.append({
                    "kind": "ckpt",
                    "step": self.cfg.total_steps,
                    "at_s": round(done_s, 6),
                    "write_s": self.ckpt_write_s,
                })
                self.ckpt_writes += 1
                self.ckpt_time_s += self.ckpt_write_s
                self.last_ckpt_step = self.cfg.total_steps
            self.done_s = round(done_s, 6)
            self.state = "done"
            metrics.train_board().incr("gangs_done")

    def _close_segment(self, now: float) -> None:
        self.ledger.append({
            "kind": "run",
            "from_step": self.seg_step0,
            "to_step": self.steps_done,
            "t0": round(self.seg_t0, 6),
            "t1": round(now, 6),
            "topology": self.topology,
            "step_s": round(self.step_s, 9),
        })
        self.seg_t0 = None

    def preempt(self, now: float, graceful: bool,
                reason: str) -> None:
        """Displacement. Graceful = the PreemptionGuard contract
        (docs/CHAOS.md): the checkpoint lands at the current (last
        completed) step before the gang leaves the hardware, so
        resume loses nothing — the in-flight partial step never
        counted, and re-deriving it is not a re-count. Hard = a
        crash with no grace: progress rolls back to the last cadence
        checkpoint and the gap is priced as lost work (the quantity
        the cadence knob trades against write cost)."""
        if self.state != "running":
            return  # already displaced/queued (or done): a no-op
        self.advance(now)
        if self.state == "done":
            return
        self._close_segment(now)
        self.evictions += 1
        if graceful:
            if self.last_ckpt_step != self.steps_done:
                self.ledger.append({
                    "kind": "ckpt", "step": self.steps_done,
                    "at_s": round(now, 6),
                    "write_s": self.ckpt_write_s,
                    "cause": "preempt",
                })
                self.ckpt_writes += 1
                self.ckpt_time_s += self.ckpt_write_s
                self.last_ckpt_step = self.steps_done
            metrics.train_board().incr("graceful_preemptions")
        else:
            lost = self.steps_done - self.last_ckpt_step
            if lost:
                self.ledger.append({
                    "kind": "rollback",
                    "from_step": self.steps_done,
                    "to_step": self.last_ckpt_step,
                    "at_s": round(now, 6),
                    "lost_steps": lost,
                })
                self.lost_steps += lost
                self.steps_done = self.last_ckpt_step
            metrics.train_board().incr("hard_kills")
        self.ledger.append({
            "kind": "evict", "step": self.steps_done,
            "at_s": round(now, 6), "reason": reason,
            "graceful": graceful,
        })
        self.state = "pending"
        metrics.recovery_log().record(
            "train_gang_evict", gang=self.cfg.name,
            step=self.steps_done, graceful=graceful,
            at_s=round(now, 6))

    def bound(self, now: float, link_factor: float,
              bind_s: float) -> float:
        """The scheduler placed (or re-placed) the gang: stepping
        resumes from the committed step after bind latency plus the
        modeled restart cost (checkpoint load + collective re-init),
        at the step time of the NEW shape and domain. Returns the
        resume instant."""
        ready = now + bind_s + self.restart_s
        self.step_s = step_time_s(self.cfg, self.topology,
                                  link_factor)
        self.seg_t0 = ready
        self.seg_step0 = self.steps_done
        self.restart_time_s += self.restart_s
        if self.first_bound_s is None:
            self.first_bound_s = round(ready, 6)
        self.state = "running"
        # re-scan the corruption horizon from the resume step: a
        # re-placed gang keeps its live defects (the chips moved
        # with the topology — only quarantine retires one)
        self._recompute_spike(self.steps_done + 1)
        self.ledger.append({
            "kind": "bind", "step": self.steps_done,
            "at_s": round(now, 6), "resume_s": round(ready, 6),
            "topology": self.topology,
            "step_s": round(self.step_s, 9),
        })
        metrics.train_board().incr("gangs_bound")
        return ready

    def reprice(self, now: float, link_factor: float) -> None:
        """The domain's link state changed mid-segment (gray
        degrade/restore): commit progress, close the segment, and
        open a new one at the new step time from ``now`` — a pure
        rate change, no checkpoint and no restart cost."""
        if self.state != "running":
            return
        new_step_s = step_time_s(self.cfg, self.topology,
                                 link_factor)
        if self.step_s == new_step_s:
            return
        self.advance(now)
        if self.state != "running":
            return
        # resume from the NEXT whole-step boundary at the new rate:
        # the in-flight partial step re-derives at the new pace
        self._close_segment(now)
        self.step_s = new_step_s
        self.seg_t0 = now
        self.seg_step0 = self.steps_done
        self.ledger.append({
            "kind": "reprice", "step": self.steps_done,
            "at_s": round(now, 6),
            "step_s": round(new_step_s, 9),
        })

    # -- reporting -------------------------------------------------

    def work_done(self) -> float:
        return self.high_water * self.cfg.work_per_step

    def report(self) -> Dict[str, object]:
        verify = verify_ledger(self.ledger, self.cfg.total_steps)
        productive = sum(
            rec["t1"] - rec["t0"] for rec in self.ledger
            if rec.get("kind") == "run")
        overhead = self.ckpt_time_s + self.restart_time_s
        span = (self.done_s - self.cfg.arrival_s
                if self.done_s is not None else None)
        out: Dict[str, object] = {
            "config": self.cfg.as_dict(),
            "mesh": gang_mesh(self.cfg.accelerator, self.topology,
                              self.cfg.kind),
            "state": self.state,
            "topology": self.topology,
            "steps_done": self.steps_done,
            "unique_steps": self.high_water,
            "lost_steps": self.lost_steps,
            "rerun_steps": verify["rerun_steps"],
            "checkpoint": {
                "every": self.ckpt_every,
                "writes": self.ckpt_writes,
                "write_s": self.ckpt_write_s,
                "time_s": round(self.ckpt_time_s, 6),
            },
            "restart_time_s": round(self.restart_time_s, 6),
            "evictions": self.evictions,
            "migrations": self.migrations,
            "grows": self.grows,
            "shrinks": self.shrinks,
            "overhead_frac": (
                round(overhead / (productive + overhead), 6)
                if productive + overhead > 0 else 0.0),
            "ledger": self.ledger,
            "ledger_verify": verify,
        }
        if self.sdc_culprits or self.sdc_chips:
            # conditional: gangs that never saw an SDC fault keep
            # their historical report bytes
            out["sdc"] = {
                "culprits": self.sdc_culprits,
                "active_defects": sorted(self.sdc_chips),
                "bisection_rounds": sum(
                    c["bisection_rounds"]
                    for c in self.sdc_culprits),
            }
        if self.done_s is not None:
            out["done_s"] = self.done_s
            out["time_to_completion_s"] = round(span, 6)
            if span and span > 0:
                out["work_per_s"] = round(
                    self.work_done() / span, 3)
                out["work_unit"] = self.cfg.work_unit
        return out


# -- the tenant manager ------------------------------------------------


@dataclasses.dataclass(frozen=True)
class TrainingConfig:
    """The fleet's training tenancy: the gangs plus the economics
    defaults every gang inherits unless it overrides them.
    ``checkpoint_every`` 0 (or the knob's 0 default) derives the
    Young-Daly optimum per gang from its own step time."""

    gangs: Tuple[TrainingGangConfig, ...] = ()
    checkpoint_every: Optional[int] = None
    checkpoint_write_s: Optional[float] = None
    restart_s: Optional[float] = None
    mtbf_s: Optional[float] = None
    elastic: Optional[bool] = None
    # scavenge growth straight from free inventory (no planner in
    # the loop); spot-grant growth (docs/GLOBE.md) works either way
    scavenge: bool = False

    def as_dict(self) -> dict:
        return {
            "gangs": [g.as_dict() for g in self.gangs],
            "checkpoint_every": self.checkpoint_every,
            "checkpoint_write_s": resolve_ckpt_write_s(
                self.checkpoint_write_s),
            "restart_s": resolve_restart_s(self.restart_s),
            "mtbf_s": resolve_mtbf_s(self.mtbf_s),
            "elastic": resolve_elastic(self.elastic),
            "scavenge": self.scavenge,
        }


class TrainingTenant:
    """The training side of one scheduler-backed fleet: submits
    gangs at arrival, receives bind/evict callbacks from the fleet
    driver, applies chaos, runs the elastic ladder at evaluation
    boundaries, and publishes the per-gang ledgers. Deterministic:
    gangs iterate in sorted name order; every decision is a pure
    function of (config, scheduler state, virtual time)."""

    def __init__(self, cfg: TrainingConfig, sched):
        self.cfg = cfg
        self.sched = sched
        write_s = resolve_ckpt_write_s(cfg.checkpoint_write_s)
        restart = resolve_restart_s(cfg.restart_s)
        self.mtbf_s = resolve_mtbf_s(cfg.mtbf_s)
        elastic = resolve_elastic(cfg.elastic)
        self.gangs: Dict[str, TrainingGang] = {}
        for g in cfg.gangs:
            every = (g.checkpoint_every
                     if g.checkpoint_every is not None
                     else cfg.checkpoint_every)
            if every is None:
                every = int(knobs.get(CKPT_EVERY_ENV))
            if every <= 0:
                every = optimal_cadence_steps(
                    step_time_s(g, g.topology),
                    (g.checkpoint_write_s
                     if g.checkpoint_write_s is not None
                     else write_s),
                    self.mtbf_s)
            name = GANG_PREFIX + g.name
            if name in self.gangs:
                raise ValueError(f"duplicate gang name {g.name!r}")
            self.gangs[name] = TrainingGang(
                g, ckpt_every=every,
                ckpt_write_s=(g.checkpoint_write_s
                              if g.checkpoint_write_s is not None
                              else write_s),
                restart_s=(g.restart_s if g.restart_s is not None
                           else restart),
                elastic=elastic)
        self._arrivals = sorted(
            self.gangs, key=lambda n: (self.gangs[n].cfg.arrival_s,
                                       n))
        self._hard_kill: Optional[str] = None
        # spot grants outstanding (the globe planner's training leg,
        # docs/GLOBE.md): one grant = one growth rung
        self.spot_granted = 0
        self._reclaim_wanted = 0

    # -- identity ---------------------------------------------------

    def owns(self, gang_name: str) -> bool:
        return gang_name in self.gangs

    def quiescent(self) -> bool:
        return all(g.state == "done"
                   for g in self.gangs.values())

    def wants_evals(self) -> bool:
        """Whether evaluation boundaries matter: the elastic ladder
        (and spot reclaim confirmation) only act there. A fully
        inelastic (or finished) tenancy needs none — the event core
        may skip its eval boundaries without divergence because
        :meth:`evaluate` would be a no-op anyway."""
        return (not self.quiescent()
                and (self._reclaim_wanted > 0
                     or any(g.elastic
                            for g in self.gangs.values()
                            if g.state != "done")))

    # -- scheduler callbacks (via the fleet driver) -----------------

    def _request(self, name: str):
        from kind_tpu_sim.sched.scheduler import SliceRequest

        gang = self.gangs[name]
        return SliceRequest(
            name=name, accelerator=gang.cfg.accelerator,
            topology=gang.topology, priority=gang.cfg.priority)

    def tick(self, now: float) -> None:
        """Per-boundary bookkeeping: submit due arrivals, commit
        closed-form progress, release completed gangs' inventory."""
        while self._arrivals:
            name = self._arrivals[0]
            if self.gangs[name].cfg.arrival_s > now:
                break
            self._arrivals.pop(0)
            self.gangs[name].state = "pending"
            self.sched.submit(self._request(name), now)
            metrics.train_board().incr("gangs_submitted")
        for name in sorted(self.gangs):
            gang = self.gangs[name]
            if gang.state != "running":
                continue
            gang.advance(now)
            if gang.state == "done":
                self.sched.release(name, now,
                                   reason="training complete")

    def on_bound(self, name: str, now: float, link_factor: float,
                 bind_s: float) -> None:
        self.gangs[name].bound(now, link_factor, bind_s)

    def on_evicted(self, name: str, now: float) -> None:
        """Preemption/node-chaos displacement (the scheduler already
        requeued the request): graceful unless a pending hard-kill
        chaos marked this gang."""
        gang = self.gangs[name]
        hard = self._hard_kill == name
        gang.preempt(now, graceful=not hard,
                     reason="hard kill" if hard else "preempted")

    def on_migrated(self, name: str, now: float,
                    link_factor: float, bind_s: float) -> None:
        """Defrag moved the gang (it is already rebound elsewhere):
        a checkpointed repartition at the same shape — checkpoint,
        restart cost, resume on the new domain's link state."""
        gang = self.gangs[name]
        gang.preempt(now, graceful=True, reason="defrag migration")
        gang.migrations += 1
        gang.bound(now, link_factor, bind_s)
        metrics.train_board().incr("migrations")

    def apply_chaos(self, action: str, target: int,
                    now: float) -> None:
        """``train_preempt`` (graceful, the spot-reclaim /
        maintenance shape) or ``train_kill`` (hard crash, loses the
        steps since the last cadence checkpoint) against gang index
        ``target`` in sorted-name order."""
        names = sorted(self.gangs)
        name = names[target % len(names)]
        gang = self.gangs[name]
        if gang.state == "done":
            return
        if gang.state != "running":
            # still queued: a preemption of nothing; a hard kill of
            # a checkpointed, unscheduled gang is also a no-op
            return
        if action == "train_kill":
            self._hard_kill = name
        try:
            self.sched.evict_gang(
                name, now,
                reason=("chaos: hard kill (no grace)"
                        if action == "train_kill"
                        else "chaos: training gang preempted"))
        finally:
            self._hard_kill = None

    def apply_sdc(self, target: int, frac: float,
                  now: float) -> None:
        """``sdc_train_chip`` chaos: seed a defective chip into gang
        index ``target`` (sorted-name order, the same addressing
        ``apply_chaos`` uses). The chip index is a crc32 draw over
        the gang's CURRENT chip count, so the culprit the bisection
        must name is itself a pure function of (gang, target)."""
        names = sorted(self.gangs)
        name = names[target % len(names)]
        gang = self.gangs[name]
        if gang.state == "done":
            return
        chips = topo.make_slice(gang.cfg.accelerator,
                                gang.topology).num_chips
        chip = zlib.crc32(
            f"sdc:{name}:{target}".encode("utf-8")) % chips
        gang.seed_defect(chip, frac, now)
        metrics.recovery_log().record(
            "train_sdc_seeded", gang=gang.cfg.name, chip=chip,
            frac=round(frac, 6), at_s=round(now, 6))

    def drain_sdc_verdicts(self) -> List[dict]:
        """Bisection verdicts since the last drain, in sorted gang
        order — the fleet driver turns each into a chip-granular
        quarantine (docs/SDC.md)."""
        out: List[dict] = []
        for name in sorted(self.gangs):
            gang = self.gangs[name]
            while gang.sdc_verdicts_out:
                verdict = gang.sdc_verdicts_out.pop(0)
                verdict["gang"] = name
                out.append(verdict)
        return out

    def evict_all(self, now: float, reason: str) -> None:
        """Blast-radius displacement (zone loss / cell failure,
        docs/GLOBE.md): every bound gang checkpoints and evicts; the
        requeued requests rebind when the cell returns."""
        for name in sorted(self.gangs):
            if name in self.sched.bound:
                self.sched.evict_gang(name, now, reason=reason)

    # -- elasticity --------------------------------------------------

    def grant_spot(self, now: float) -> None:
        """The planner granted one spot growth rung."""
        self.spot_granted += 1
        metrics.train_board().incr("spot_grants")

    def reclaim_spot(self, now: float) -> None:
        """The planner wants one rung back. An UNUSED rung returns
        immediately; a consumed one is flagged — the next evaluation
        shrinks a grown gang (never aborts it) and the grant is only
        counted returned once :meth:`spot_in_use` reflects the
        shrink."""
        if self.spot_granted <= 0:
            return
        in_use = self.spot_in_use()
        if self.spot_granted > in_use:
            self.spot_granted -= 1
            metrics.train_board().incr("spot_returns")
            return
        if self._reclaim_wanted < in_use:
            self._reclaim_wanted += 1
            metrics.train_board().incr("spot_reclaims")

    def spot_in_use(self) -> int:
        return sum(g.spot_rungs for g in self.gangs.values())

    def wants_spot(self) -> bool:
        """Whether a grant could actually be consumed: some elastic,
        unfinished gang has ladder headroom AND the grown shape is
        feasibly placeable right now — the planner must not park
        budget on a tenant that cannot use it."""
        return any(
            g.elastic and g.state == "running"
            and self._feasible_grow(g) is not None
            for g in self.gangs.values())

    def _growable(self, gang: TrainingGang) -> Optional[str]:
        grown = grow_topology(gang.cfg.accelerator, gang.topology)
        if grown is None:
            return None
        if gang.cfg.max_topology is not None:
            cap = topo.make_slice(gang.cfg.accelerator,
                                  gang.cfg.max_topology).num_chips
            if topo.make_slice(gang.cfg.accelerator,
                               grown).num_chips > cap:
                return None
        return grown

    def _feasible_grow(self, gang: TrainingGang) -> Optional[str]:
        """The ladder's next rung IF the grown shape has a feasible
        placement in the current inventory (never counting on
        eviction — training scavenges, it does not displace)."""
        grown = self._growable(gang)
        if grown is None:
            return None
        grown_slice = topo.make_slice(gang.cfg.accelerator, grown)
        cands = self.sched.inv.candidate_placements(
            accelerator=gang.cfg.accelerator,
            host_block=grown_slice.host_grid,
            chips_per_node=grown_slice.chips_per_host)
        return grown if cands else None

    def _resize(self, name: str, new_topology: str,
                now: float) -> None:
        """Checkpointed repartition: evict (graceful checkpoint),
        withdraw the auto-requeued old-shape request, resubmit at
        the new shape — the next scheduling pass rebinds and the
        gang resumes with the restart cost."""
        gang = self.gangs[name]
        if name in self.sched.bound:
            self.sched.evict_gang(
                name, now,
                reason=f"elastic resize {gang.topology} "
                       f"-> {new_topology}")
        self.sched.withdraw(name, now, reason="resize resubmit")
        gang.topology = new_topology
        gang.ledger.append({
            "kind": "resize", "step": gang.steps_done,
            "at_s": round(now, 6), "topology": new_topology,
        })
        self.sched.submit(self._request(name), now)

    def evaluate(self, now: float) -> None:
        """The elastic ladder, on the fleet's evaluation cadence.
        Shrinks serve reclaim debt first (shrink-never-abort: the
        floor is the base shape); grows spend spot rungs, or
        scavenge free inventory when ``TrainingConfig.scavenge`` —
        and only ever onto capacity that is feasibly placeable RIGHT
        NOW, so a grow can never strand a gang in the queue."""
        if not self.wants_evals():
            return
        for name in sorted(self.gangs):
            if self._reclaim_wanted <= 0:
                break
            gang = self.gangs[name]
            if gang.state == "done" or gang.spot_rungs <= 0:
                continue
            shrunk = shrink_topology(gang.cfg.accelerator,
                                     gang.topology,
                                     gang.cfg.topology)
            if shrunk is None:
                continue
            self._resize(name, shrunk, now)
            gang.spot_rungs -= 1
            gang.shrinks += 1
            self.spot_granted -= 1
            self._reclaim_wanted -= 1
            metrics.train_board().incr("shrinks")
        for name in sorted(self.gangs):
            gang = self.gangs[name]
            if (not gang.elastic or gang.state != "running"):
                continue
            spot_ok = self.spot_granted > self.spot_in_use()
            if not (spot_ok or self.cfg.scavenge):
                continue
            grown = self._feasible_grow(gang)
            if grown is None:
                continue  # nothing scavengeable without eviction
            self._resize(name, grown, now)
            gang.grows += 1
            if spot_ok:
                gang.spot_rungs += 1
            metrics.train_board().incr("grows")
        # hand back rungs nothing here can use (the planner settles
        # them on its next pass) — budget must never idle on a
        # tenant with no feasible growth
        while (self.spot_granted > self.spot_in_use()
               and not self.wants_spot()):
            self.spot_granted -= 1
            metrics.train_board().incr("spot_returns")

    # -- event-core plumbing ----------------------------------------

    def due(self, due_set) -> None:
        """Contribute this tenant's boundary-condition instants:
        gang arrivals and segment completions (a completed gang
        releases inventory, which can unblock the pending queue).
        Everything else — checkpoint boundaries, mid-segment
        progress — is closed form and needs no stepping."""
        for name in self._arrivals[:1]:
            due_set.at(self.gangs[name].cfg.arrival_s)
        for name in sorted(self.gangs):
            # spike-aware: an SDC detection point is a boundary-
            # condition event exactly like a completion
            due_set.at(self.gangs[name].next_event_s())

    # -- reporting ---------------------------------------------------

    def report(self) -> Dict[str, object]:
        gangs = {name[len(GANG_PREFIX):]: g.report()
                 for name, g in sorted(self.gangs.items())}
        verify_ok = all(g["ledger_verify"]["ok"]
                        for g in gangs.values())
        return {
            "gangs": gangs,
            "all_done": self.quiescent(),
            "ledger_ok": verify_ok,
            "lost_steps": sum(g["lost_steps"]
                              for g in gangs.values()),
            "rerun_steps": sum(g["rerun_steps"]
                               for g in gangs.values()),
            "evictions": sum(g["evictions"]
                             for g in gangs.values()),
            "migrations": sum(g["migrations"]
                              for g in gangs.values()),
            "checkpoint_writes": sum(
                g["checkpoint"]["writes"] for g in gangs.values()),
            "grows": sum(g["grows"] for g in gangs.values()),
            "shrinks": sum(g["shrinks"] for g in gangs.values()),
            "spot": {"granted": self.spot_granted,
                     "in_use": self.spot_in_use()},
            "mtbf_s": self.mtbf_s,
        }


# -- the kubernetes face (pods/tpu-batch-train-job.yaml) ---------------


def gangs_from_manifest(text: str) -> List[TrainingGangConfig]:
    """Parse a kubernetes manifest's TPU training workloads into
    training-tenant specs — the same StatefulSet-is-one-gang mapping
    :mod:`kind_tpu_sim.sched.kubeface` applies (all-or-nothing
    multi-host worlds), carrying the priority tier through. This is
    what lets ``pods/tpu-batch-train-job.yaml`` drive the sim
    instead of sitting unused."""
    from kind_tpu_sim.sched import kubeface

    out: List[TrainingGangConfig] = []
    for req in kubeface.slice_requests_from_yaml(text):
        out.append(TrainingGangConfig(
            name=req.name, accelerator=req.accelerator,
            topology=req.topology, priority=req.priority))
    return out


def to_manifest(cfg: TrainingGangConfig) -> str:
    """Render a training-tenant spec back to schedulable YAML (a
    StatefulSet gang for multi-host shapes) — the round-trip inverse
    of :func:`gangs_from_manifest`:
    ``gangs_from_manifest(to_manifest(cfg))`` reproduces the
    scheduling-relevant fields."""
    from kind_tpu_sim.sched import kubeface
    from kind_tpu_sim.sched.scheduler import SliceRequest

    return kubeface.to_pod_manifest(SliceRequest(
        name=cfg.name, accelerator=cfg.accelerator,
        topology=cfg.topology, priority=cfg.priority))
