"""Columnar (struct-of-arrays) mirror of an all-analytic fleet.

The event core (PR 8) made wall time scale with EVENT count, but
each stepped boundary still paid O(replicas) in Python: the wake
scan called ``next_due()`` on every replica, the tick fan-out
called ``tick()`` on every replica (almost all of them no-ops),
and every routed request sorted the whole replica list. At 10k
replicas those per-object scans dominate the per-event cost.

:class:`FleetColumns` keeps the scheduling-relevant state of every
:class:`~kind_tpu_sim.fleet.router.SimReplica` in numpy arrays —
wake bounds (the ``(ge, cover)`` pair ``next_due()`` computes),
queue length, outstanding count, health — refreshed lazily through
a dirty set the replicas themselves maintain (every mutating
replica method calls ``_touch()``). The three hot paths become
array reductions:

* the wake scan is ``min()`` over the ge/cover columns,
* the tick fan-out visits only replicas that can act in the
  window — queued work, in-flight slots, or a covering bound
  inside it; skipping the idle rest is exactly the event core's
  partition-invariance argument applied per replica (an idle
  replica's tick is a strict no-op, and busy replicas are visited
  every stepped boundary so their token chains materialize at the
  same rate the per-object path fixes them — load-bearing when
  gray chaos changes a replica's service rate mid-run),
* least-outstanding routing is one masked ``argmin`` over the key
  ``outstanding * K + replica_id`` (the same (load, id) tiebreak
  the sorted path uses).

Reports stay byte-identical with the columns on or off — the knob
``KIND_TPU_SIM_FLEET_COLUMNAR`` (default on) reverts to the
per-object paths, and the A/B identity is pinned by tests.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from kind_tpu_sim.analysis import knobs

COLUMNAR_ENV = knobs.FLEET_COLUMNAR

_INF = float("inf")
# default-engagement floor: below this many replicas the per-object
# scans are already cheap and the per-boundary numpy call overhead
# (flush + wake + fan-out masks) costs more than it saves — the
# default (knob-driven) path only engages at or above it. Purely a
# cost heuristic: reports are byte-identical either side, and an
# explicit FleetConfig.columnar=True engages at any size (the A/B
# identity tests rely on that).
COLUMNAR_MIN_REPLICAS = 32
# masked-out (unhealthy) entries in the routing argmin key: far
# above any reachable outstanding*K+id value, still int64-safe
_MASKED = np.int64(1) << np.int64(62)


def resolve_columnar(value: Optional[bool] = None) -> bool:
    """Explicit value > env (KIND_TPU_SIM_FLEET_COLUMNAR) > on."""
    if value is not None:
        return bool(value)
    return bool(knobs.get(COLUMNAR_ENV))


class FleetColumns:
    """The struct-of-arrays mirror. Indexed by LIST POSITION in the
    fleet's replica list (which the driver keeps id-sorted), so the
    tick fan-out preserves the per-object loop's iteration order —
    completion observation order is part of the replay contract."""

    __slots__ = ("replicas", "n", "ge", "cover", "qlen", "out",
                 "healthy", "ids", "_key_base", "dirty")

    def __init__(self, replicas: Sequence):
        self.replicas: List = []
        self.rebuild(replicas)

    def rebuild(self, replicas: Sequence) -> None:
        """Re-mirror after a membership change (scale events). Rare
        by construction — the autoscaler acts at eval cadence."""
        new = list(replicas)
        keep = {id(r) for r in new}
        for r in self.replicas:
            if id(r) not in keep:
                r._cols = None
        self.replicas = new
        n = len(new)
        self.n = n
        self.ge = np.full(n, _INF)
        self.cover = np.full(n, _INF)
        self.qlen = np.zeros(n, dtype=np.int64)
        self.out = np.zeros(n, dtype=np.int64)
        self.healthy = np.zeros(n, dtype=bool)
        self.ids = np.array([r.replica_id for r in new],
                            dtype=np.int64).reshape(n)
        self._key_base = (int(self.ids.max()) + 1) if n else 1
        for i, r in enumerate(new):
            r._cols = self
            r._idx = i
        self.dirty = set(range(n))

    def flush(self) -> None:
        """Refresh every dirty row from its replica — O(touched),
        not O(replicas): the lazy half of the design."""
        d = self.dirty
        if not d:
            return
        reps = self.replicas
        ge, cover = self.ge, self.cover
        qlen, out, healthy = self.qlen, self.out, self.healthy
        for i in d:
            r = reps[i]
            g, c = r.next_due()
            ge[i] = _INF if g is None else g
            cover[i] = _INF if c is None else c
            qlen[i] = len(r.queue)
            out[i] = r.outstanding()
            healthy[i] = r.healthy
        d.clear()

    # -- the vectorized hot paths -------------------------------------

    def wake(self) -> tuple:
        """(ge_min, cover_min) across the fleet — the replica leg of
        the event core's wake scan, as two array reductions."""
        self.flush()
        if not self.n:
            return (None, None)
        g = float(self.ge.min())
        c = float(self.cover.min())
        return (None if g == _INF else g,
                None if c == _INF else c)

    def active_indices(self, end: float) -> Sequence[int]:
        """List positions (ascending — the fan-out order contract)
        of replicas whose ``tick()`` over a window ending at ``end``
        is not provably a no-op: queued work acts at every boundary
        (admission / deadline reaping), in-flight slots materialize
        their token chain boundary-by-boundary, and a covering bound
        inside the window means an externally visible slot event may
        land. Only an IDLE replica's visit is provably a no-op —
        deferring a busy replica's internal token events is safe
        only while its service rate is constant, and the gray-chaos
        ``slow``/``unslow`` (and degraded-link) actions change the
        rate mid-run: a deferred link would then be scheduled at the
        new factor where the per-object path already fixed it at the
        old one."""
        self.flush()
        if not self.n:
            return ()
        mask = ((self.qlen > 0) | (self.out > 0)
                | (self.healthy & (self.cover <= end)))
        return np.nonzero(mask)[0]

    def all_idle(self) -> bool:
        """Quiescence's replica leg: no healthy replica holds work."""
        self.flush()
        if not self.n:
            return True
        return not bool((self.out[self.healthy] > 0).any())

    def healthy_outstanding(self) -> int:
        """Sum of outstanding over healthy replicas (the autoscaler
        backlog term)."""
        self.flush()
        if not self.n:
            return 0
        return int(self.out[self.healthy].sum())

    def pick_least_outstanding(self):
        """The routing fast path: the healthy replica minimizing
        (outstanding, replica_id) — identical to the sorted path's
        first candidate — or None when no replica is healthy."""
        self.flush()
        if not self.n:
            return None
        key = np.where(self.healthy,
                       self.out * self._key_base + self.ids,
                       _MASKED)
        i = int(key.argmin())
        if key[i] >= _MASKED:
            return None
        return self.replicas[i]
