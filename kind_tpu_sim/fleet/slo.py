"""Streaming SLO accounting: percentiles without unbounded state.

The serving engine's host-side latency window (serving.py) keeps a
deque and sorts it per report — fine for one engine, wrong for a fleet
soak that completes millions of requests. Here percentiles come from
:class:`FixedBucketHistogram`: geometric buckets over a fixed range,
O(buckets) memory forever, observe() is one bisect, percentile() is
one cumulative scan. The price is bounded relative error (one bucket
width, ~``growth - 1``); tests/test_fleet.py pins the histogram
against a brute-force sorted reference at that tolerance.

:class:`SloTracker` layers attainment and goodput on top: a request
ATTAINS when every configured target (TTFT, TPOT, e2e) holds and it
was neither shed nor deadline-expired. Goodput counts only attained
requests' tokens; throughput counts everything that completed — the
gap between the two is the number the router/autoscaler policies are
judged on (a fleet can have great throughput and terrible goodput by
letting queues grow).
"""

from __future__ import annotations

import bisect
import dataclasses
import math
from typing import Dict, List, Optional, Sequence


class FixedBucketHistogram:
    """Geometric fixed-bucket histogram over (0, hi].

    Bucket upper bounds grow by ``growth`` from ``lo`` to ``hi``;
    values <= lo land in the first bucket, values > hi in a final
    overflow bucket (its reported bound is the largest value seen, so
    an outlier is visible, never silently clamped). ``percentile``
    returns the upper bound of the bucket where the cumulative count
    crosses rank — the usual Prometheus-style upper-bound estimate,
    biased high by at most one bucket width."""

    def __init__(self, lo: float = 1e-4, hi: float = 1e3,
                 growth: float = 1.12):
        if not (0 < lo < hi and growth > 1.0):
            raise ValueError(
                f"need 0 < lo < hi and growth > 1; got lo={lo} "
                f"hi={hi} growth={growth}")
        bounds: List[float] = []
        b = lo
        while b < hi:
            bounds.append(b)
            b *= growth
        bounds.append(hi)
        self.bounds = bounds              # bucket upper bounds
        self.counts = [0] * (len(bounds) + 1)  # + overflow
        self.total = 0
        self._max = 0.0
        self._sum = 0.0

    def observe(self, value: float, count: int = 1) -> None:
        """Record ``count`` occurrences of ``value`` (one bisect
        either way — the weighted form is how the ITL histogram
        ingests a request's ``tokens - 1`` identical gaps without a
        per-token loop)."""
        if value < 0 or math.isnan(value):
            raise ValueError(f"bad latency sample {value!r}")
        idx = bisect.bisect_left(self.bounds, value)
        self.counts[idx] += count
        self.total += count
        self._sum += value * count
        if value > self._max:
            self._max = value

    def percentile(self, p: float) -> Optional[float]:
        """Upper-bound estimate of the p-quantile (p in [0, 1]);
        None on an empty histogram."""
        if not self.total:
            return None
        rank = p * self.total
        cum = 0
        for idx, count in enumerate(self.counts):
            cum += count
            if cum >= rank and count:
                if idx >= len(self.bounds):
                    return self._max  # overflow: report the max seen
                return min(self.bounds[idx], self._max)
        return self._max

    @property
    def mean(self) -> Optional[float]:
        return self._sum / self.total if self.total else None

    @property
    def max(self) -> Optional[float]:
        return self._max if self.total else None

    def report(self) -> Dict[str, float]:
        if not self.total:
            return {"count": 0}
        return {
            "count": self.total,
            "p50_s": round(self.percentile(0.50), 6),
            "p90_s": round(self.percentile(0.90), 6),
            "p99_s": round(self.percentile(0.99), 6),
            "mean_s": round(self.mean, 6),
            "max_s": round(self._max, 6),
        }


@dataclasses.dataclass(frozen=True)
class SloPolicy:
    """Per-request latency targets (None = dimension unconstrained).
    ``ttft_s`` bounds queue wait + prefill; ``tpot_s`` bounds the
    mean time per post-first output token (the streaming smoothness
    target); ``e2e_s`` bounds submit -> finish."""

    ttft_s: Optional[float] = None
    tpot_s: Optional[float] = None
    e2e_s: Optional[float] = None
    # inter-token latency target — the decode-pool autoscaling
    # signal (docs/DISAGG.md). Per-request it is the same quantity
    # as tpot (the mean post-first gap), so it does NOT double-count
    # in attained(); it gates the disagg driver's ITL breach window
    # and the token-weighted itl histogram instead.
    itl_s: Optional[float] = None

    def attained(self, ttft: float, tpot: Optional[float],
                 e2e: float) -> bool:
        if self.ttft_s is not None and ttft > self.ttft_s:
            return False
        if (self.tpot_s is not None and tpot is not None
                and tpot > self.tpot_s):
            return False
        if self.e2e_s is not None and e2e > self.e2e_s:
            return False
        return True


class SloTracker:
    """Streaming per-completion SLO accounting for one fleet run.

    ``observe()`` ingests one completion's virtual-time line (arrival,
    first token, finish, token count, failure flags); ``report()``
    emits the attainment / goodput / percentile summary. All state is
    bounded: three histograms plus a handful of counters."""

    def __init__(self, policy: SloPolicy,
                 hist_lo: float = 1e-4, hist_hi: float = 1e3,
                 track_itl: bool = False):
        self.policy = policy
        self.ttft = FixedBucketHistogram(hist_lo, hist_hi)
        self.tpot = FixedBucketHistogram(hist_lo, hist_hi)
        self.e2e = FixedBucketHistogram(hist_lo, hist_hi)
        # first-class ITL histogram (docs/DISAGG.md): the tpot
        # histogram weights every REQUEST equally; this one weights
        # every TOKEN GAP equally (a 100-token answer contributes 99
        # observations), which is what a decode pool's smoothness
        # actually looks like to a streaming client. Opt-in —
        # reports without it stay byte-identical to pre-disagg runs.
        self.track_itl = track_itl
        self.itl = FixedBucketHistogram(hist_lo, hist_hi)
        self.completed = 0
        self.attained = 0
        self.shed = 0
        self.expired = 0
        self.tokens_total = 0
        self.tokens_good = 0
        self._span_end = 0.0

    def observe(self, *, arrival_s: float, first_s: Optional[float],
                finish_s: float, tokens: int, shed: bool = False,
                deadline_exceeded: bool = False) -> bool:
        """Record one terminal request outcome; returns whether it
        attained the SLO. Shed requests never produced tokens but DO
        count in the attainment denominator — shedding is an SLO
        miss the policy chose, not a request that never happened."""
        self._span_end = max(self._span_end, finish_s)
        if shed:
            self.shed += 1
            self.completed += 1
            return False
        ttft = (first_s if first_s is not None else finish_s) \
            - arrival_s
        e2e = finish_s - arrival_s
        tpot = ((finish_s - first_s) / (tokens - 1)
                if first_s is not None and tokens > 1 else None)
        self.ttft.observe(ttft)
        self.e2e.observe(e2e)
        if tpot is not None:
            self.tpot.observe(tpot)
            if self.track_itl:
                self.itl.observe(tpot, count=tokens - 1)
        self.completed += 1
        self.tokens_total += tokens
        if deadline_exceeded:
            self.expired += 1
            return False
        ok = self.policy.attained(ttft, tpot, e2e)
        if ok:
            self.attained += 1
            self.tokens_good += tokens
        return ok

    @property
    def attainment(self) -> Optional[float]:
        if not self.completed:
            return None
        return self.attained / self.completed

    def report(self, span_s: Optional[float] = None) -> Dict[str, object]:
        """``span_s`` is the virtual duration goodput/throughput are
        normalized over (default: the last finish time seen)."""
        span = span_s if span_s else self._span_end
        out: Dict[str, object] = {
            "policy": {
                k: v for k, v in dataclasses.asdict(
                    self.policy).items() if v is not None},
            "completed": self.completed,
            "attained": self.attained,
            "attainment": (round(self.attainment, 6)
                           if self.completed else None),
            "shed": self.shed,
            "deadline_exceeded": self.expired,
            "ttft": self.ttft.report(),
            "tpot": self.tpot.report(),
            "e2e": self.e2e.report(),
        }
        if self.track_itl:
            out["itl"] = self.itl.report()
        if span and span > 0:
            out["throughput_tok_s"] = round(
                self.tokens_total / span, 3)
            out["goodput_tok_s"] = round(self.tokens_good / span, 3)
        return out


def brute_force_percentile(samples: Sequence[float],
                           p: float) -> Optional[float]:
    """The reference the histogram is tested against: nearest-rank
    percentile over a sorted copy (the thing a fleet must NOT do at
    scale, kept here for the correctness test)."""
    if not samples:
        return None
    ordered = sorted(samples)
    rank = max(0, math.ceil(p * len(ordered)) - 1)
    return ordered[rank]
