"""SLO-aware routing over N serving replicas (the fleet data plane).

Two replica flavors behind one interface:

* :class:`SimReplica` — an analytic continuous-batching model on the
  virtual clock (prefill = base + per-token, decode = TPOT per token,
  ``max_slots`` concurrency, admission at tick boundaries — the same
  scheduling shape as ``ServingEngine`` without the matmuls). This is
  the SCALE-Sim move: fleet questions (replicas vs tail latency,
  policy vs goodput) become testable in milliseconds on any host.
* :class:`EngineReplica` — a real ``models/serving.ServingEngine``
  driven one ``step_round()`` per tick with its latency clocks bound
  to the virtual clock, so real token streams flow under fleet
  traffic and the chaos scenarios exercise the true slot-failure
  recovery machinery.

:class:`Router` implements the balancing policies (round-robin,
least-outstanding, prefix-affinity over the shared-prefix cohorts),
per-request deadlines while queued, and admission control: a bounded
central queue sheds loudly (the fleet face of the engine's
``EngineSaturated``), and a replica that refuses a submit (its own
``max_queue``) just falls back to the next candidate. A failed
replica's displaced requests requeue at the FRONT of the central
queue — recovery preserves FCFS as seen by the survivors.
"""

from __future__ import annotations

import dataclasses
import zlib
from typing import Dict, List, Optional, Sequence

from kind_tpu_sim import metrics
from kind_tpu_sim.fleet.loadgen import TraceRequest
from kind_tpu_sim.fleet.tenancy import tenant_of

POLICIES = ("round-robin", "least-outstanding", "prefix-affinity")


@dataclasses.dataclass(frozen=True)
class ReplicaCompletion:
    """One request's terminal outcome at a replica, on the virtual
    clock. ``tokens_crc`` fingerprints the emitted stream (crc32 of
    the token list for engine replicas; of (request_id, seed, tokens)
    for sim replicas) so stream-identity invariants don't require
    dumping every token into the log."""

    request: TraceRequest
    dispatch_s: float
    first_s: Optional[float]
    finish_s: float
    tokens: int
    tokens_crc: int
    finish_reason: str  # length | stop | deadline_exceeded
    # silent data corruption (docs/SDC.md): ground truth that this
    # stream's fingerprint is wrong — consumers must NOT branch on it
    # (detection works from tokens_crc comparison); it exists so the
    # no-corruption-escapes invariant can audit the auditors
    corrupted: bool = False


@dataclasses.dataclass(frozen=True)
class SimReplicaConfig:
    """The analytic replica's service model. Defaults approximate the
    repo's measured small-model serving numbers (docs/PERFORMANCE.md)
    scaled to round figures; fleet conclusions should come from
    RELATIVE comparisons at fixed config, not these absolutes."""

    max_slots: int = 4
    prefill_base_s: float = 0.010
    prefill_per_tok_s: float = 0.001
    tpot_s: float = 0.005
    max_queue: int = 64          # submit() refuses beyond this
    prefix_cache_entries: int = 8  # prefix groups remembered (0=off)
    # model zoo (docs/ZOO.md): per-model pricing overrides as sorted
    # (name, value) pairs — empty on every unzooed replica, which
    # keeps the plain paths (and their floats) byte-identical. A
    # model absent from the maps cannot be served here (it does not
    # fit this generation's HBM). ``model_swap_s`` is the modeled
    # weight-load time a cold admission pays; ``resident_model`` is
    # the model warm at bring-up.
    model_prefill_per_tok_s: tuple = ()
    model_tpot_s: tuple = ()
    model_swap_s: tuple = ()
    resident_model: str = ""

    def as_dict(self) -> dict:
        """The config-report form: zoo fields join only when set, so
        every unzooed report keeps its historical bytes (the same
        conditional-wire-format contract as TraceRequest)."""
        out = dataclasses.asdict(self)
        if not self.model_tpot_s:
            for key in ("model_prefill_per_tok_s", "model_tpot_s",
                        "model_swap_s", "resident_model"):
                del out[key]
        else:
            for key in ("model_prefill_per_tok_s", "model_tpot_s",
                        "model_swap_s"):
                out[key] = [list(pair) for pair in out[key]]
        return out


class SimReplica:
    """Deterministic service-time model of one continuous-batching
    engine. Slots run independent (prefill -> decode) timelines in
    CLOSED FORM: every slot carries the absolute virtual time of its
    next event (first token, next decoded token), so advancing a slot
    over [t0, t1] produces the identical floats whether the span is
    covered by one ``tick()`` call or a hundred — the partition
    invariance the event core (docs/PERFORMANCE.md "The event core")
    rests on. Admission and queue-deadline reaping happen at tick
    boundaries, like the engine's chunk-boundary scheduling."""

    def __init__(self, replica_id: int,
                 cfg: SimReplicaConfig = SimReplicaConfig(),
                 phase: str = "unified"):
        if phase not in ("prefill", "decode", "unified"):
            raise ValueError(
                f"unknown replica phase {phase!r}; known: "
                "prefill, decode, unified")
        self.replica_id = replica_id
        self.cfg = cfg
        # disaggregated serving role (docs/DISAGG.md): a ``prefill``
        # replica completes requests at the first-token event (reason
        # ``prefill_done`` — the fleet driver turns it into a KV
        # transfer); a ``decode`` replica admits KvHandoffs whose
        # prefill already happened elsewhere; ``unified`` is the
        # historical monolithic engine, byte-identical to every
        # pre-disagg replay.
        self.phase = phase
        self.healthy = True
        # gray-failure lever (docs/HEALTH.md): a multiplicative
        # service-time inflation — 1.0 is nominal; the slow_replica
        # chaos kind and degraded-ICI-domain placement both set it
        self.slowdown = 1.0
        # silent-data-corruption lever (docs/SDC.md): the sdc_chip
        # chaos kind sets the deterministic fraction of completions
        # this replica corrupts — latency stays nominal, only the
        # token fingerprint goes wrong, and unlike every windowed
        # fault it stays set until quarantine pulls the chip
        self.corrupt_frac = 0.0
        self.queue: List[TraceRequest] = []
        self._slots: List[Optional[dict]] = [None] * cfg.max_slots
        # group id -> True, LRU-bounded: the PrefixCache stand-in
        # (a hit skips the group prefix's share of prefill time)
        self._prefix_seen: Dict[int, bool] = {}
        # tenancy (docs/TENANCY.md): group id -> owning tenant, plus
        # the per-tenant entry caps the fleet driver installs when a
        # tenant declares kv_budget_frac < 1 — a hot tenant then
        # evicts its OWN oldest cohort before touching a neighbor's
        self._prefix_owner: Dict[int, str] = {}
        self.tenant_prefix_caps: Optional[Dict[str, int]] = None
        self.prefix_hits = 0
        self.prefix_misses = 0
        # model zoo (docs/ZOO.md): which model's weights are resident
        # (the warm pool), the per-model pricing views, and the swap
        # ledger. All empty/zero on an unzooed replica.
        self.resident_model = cfg.resident_model
        self._model_prefill = dict(cfg.model_prefill_per_tok_s)
        self._model_tpot = dict(cfg.model_tpot_s)
        self._model_swap = dict(cfg.model_swap_s)
        self.swaps = 0
        self.warm_hits = 0
        # fleet-driver hook: called (SwapEvent) when an admission
        # triggers a weight load — the driver schedules it on
        # LANE_MODEL_SWAP (bookkeeping-only; the latency is already
        # in the slot's closed-form timeline)
        self.on_swap = None
        # columnar mirror back-pointer (fleet/columnar.py): every
        # mutating method marks its row dirty so the fleet's arrays
        # refresh lazily; None outside a columnar fleet
        self._cols = None
        self._idx = -1

    def _touch(self) -> None:
        c = self._cols
        if c is not None:
            c.dirty.add(self._idx)

    def set_slowdown(self, factor: float) -> None:
        """Inflate (or restore, factor=1) this replica's service
        times: prefill and TPOT both scale. Applies to work admitted
        OR tokens scheduled after the call — the gray fault is a
        property of the hardware, not of individual requests. An
        already-scheduled in-flight token keeps its event time (the
        remainder-carry semantics the gray scenarios were built on);
        every subsequent token picks up the new factor."""
        self.slowdown = max(1.0, float(factor))
        self._touch()

    def set_corrupt(self, frac: float) -> None:
        """Silent data corruption (docs/SDC.md): make this replica's
        chip defective — a deterministic ``frac`` of the completions
        it produces carry a wrong (replica-keyed) token fingerprint
        while every timing stays nominal. 0.0 restores clean output
        (the chip-replaced path)."""
        self.corrupt_frac = max(0.0, min(1.0, float(frac)))
        self._touch()

    def cancel(self, request_id: str) -> bool:
        """First-completion-wins cancellation (the hedging layer's
        lever, docs/OVERLOAD.md — the WorkerCancelled read-cancel
        precedent at replica granularity): drop the request from the
        queue or free its slot mid-stream. Returns whether anything
        was actually cancelled; a request already completed (or
        never here) returns False so the caller can dedupe the late
        completion instead."""
        for i, req in enumerate(self.queue):
            if req.request_id == request_id:
                del self.queue[i]
                self._touch()
                return True
        for i, slot in enumerate(self._slots):
            if (slot is not None
                    and slot["req"].request_id == request_id):
                # the slot frees at the cancel boundary; its partial
                # stream is discarded (the winner's stream is the
                # request's one true output)
                self._slots[i] = None
                self._touch()
                return True
        return False

    def warm_prefix(self, group: int) -> None:
        """Pre-warm one prefix-cache group (the cross-cell failover
        warm-up, docs/OVERLOAD.md): the group enters the LRU as if
        just seen, without counting a hit or a miss — the next real
        request of the cohort prefills suffix-only."""
        if self.cfg.prefix_cache_entries <= 0 or group < 0:
            return
        self._prefix_seen.pop(group, None)
        self._prefix_seen[group] = True
        while len(self._prefix_seen) > self.cfg.prefix_cache_entries:
            evicted = next(iter(self._prefix_seen))
            self._prefix_seen.pop(evicted)
            self._prefix_owner.pop(evicted, None)

    # -- model zoo (docs/ZOO.md) -------------------------------------

    def can_serve(self, model: str) -> bool:
        """Zoo placement constraint: a named model must appear in
        this replica's pricing maps — absence means it does not fit
        the replica's generation HBM. The empty model (every unzooed
        request) and an unzooed replica (empty maps) serve
        anywhere."""
        return (not model or not self._model_tpot
                or model in self._model_tpot)

    def _swap_in(self, model: str, now: float) -> float:
        """Charge the weight load when an admitted model differs
        from the resident one. Residency flips AT admission (the
        load starts immediately; same-model admissions behind it are
        warm), and the driver hears about it through ``on_swap`` so
        the swap lands on LANE_MODEL_SWAP — bookkeeping only, the
        returned seconds are already folded into the slot's
        closed-form timeline."""
        if not model or not self._model_tpot:
            return 0.0
        if model == self.resident_model:
            self.warm_hits += 1
            return 0.0
        cost = self._model_swap.get(model, 0.0) * self.slowdown
        evicted = self.resident_model
        self.resident_model = model
        self.swaps += 1
        self._touch()
        if self.on_swap is not None:
            from kind_tpu_sim.fleet.zoo import SwapEvent

            self.on_swap(SwapEvent(
                replica_id=self.replica_id, model=model,
                evicted=evicted, ready_s=round(now + cost, 9)))
        return cost

    # -- replica interface -------------------------------------------

    def outstanding(self) -> int:
        return (len(self.queue)
                + sum(1 for s in self._slots if s is not None))

    def idle(self) -> bool:
        return self.outstanding() == 0

    def submit(self, req: TraceRequest, now: float) -> bool:
        if not self.healthy:
            return False
        if not self.can_serve(getattr(req, "model", "")):
            return False
        if (self.cfg.max_queue
                and len(self.queue) >= self.cfg.max_queue):
            return False
        self.queue.append(req)
        self._touch()
        return True

    def _prefill_cost(self, req: TraceRequest) -> float:
        """Full-prompt prefill time, minus the cached prefix share on
        a group hit (the PrefixCache analog, group-granular)."""
        toks = len(req.prompt)
        if (self.cfg.prefix_cache_entries > 0
                and req.prefix_group >= 0):
            if req.prefix_group in self._prefix_seen:
                self.prefix_hits += 1
                # LRU refresh, like PrefixCache.lookup's move_to_end
                self._prefix_seen.pop(req.prefix_group)
                self._prefix_seen[req.prefix_group] = True
                # suffix-only prefill: the group prefix's tokens are
                # already cached rows (serving._suffix_into_slot)
                toks = max(1, toks - self._group_prefix_len(req))
            else:
                self.prefix_misses += 1
                self._prefix_seen[req.prefix_group] = True
                caps = self.tenant_prefix_caps
                if caps is not None:
                    # tenant-budgeted insertion: charge the cohort to
                    # its tenant and evict that tenant's own LRU
                    # entries past its cap — isolation means a noisy
                    # tenant thrashes only its own cache share
                    owner = tenant_of(req)
                    self._prefix_owner[req.prefix_group] = owner
                    cap = caps.get(owner)
                    if cap is not None:
                        owned = [g for g in self._prefix_seen
                                 if self._prefix_owner.get(g)
                                 == owner]
                        while len(owned) > cap:
                            g = owned.pop(0)
                            self._prefix_seen.pop(g, None)
                            self._prefix_owner.pop(g, None)
                while (len(self._prefix_seen)
                       > self.cfg.prefix_cache_entries):
                    evicted = next(iter(self._prefix_seen))
                    self._prefix_seen.pop(evicted)
                    self._prefix_owner.pop(evicted, None)
        # per-model prefill rate (docs/ZOO.md); the .get default IS
        # the config float, so unzooed replicas keep identical math
        per_tok = self._model_prefill.get(
            req.model, self.cfg.prefill_per_tok_s)
        return (self.cfg.prefill_base_s
                + per_tok * toks) * self.slowdown

    @staticmethod
    def _group_prefix_len(req: TraceRequest) -> int:
        """Shared-prefix length: the loadgen contract says grouped
        prompts share their leading segment; we credit at most half
        the prompt so a hit never zeroes prefill entirely."""
        return min(len(req.prompt) // 2, 16)

    def next_due(self) -> tuple:
        """``(ge_s, cover_s)`` — the event core's view of this
        replica (docs/PERFORMANCE.md "The event core"). ``ge_s`` is
        the earliest *boundary-condition* instant (a queued request's
        deadline expiry, or 0.0 when queued work can admit into a
        free slot at the very next boundary). ``cover_s`` is a SAFE
        LOWER BOUND on the earliest externally visible in-slot event
        — a request completing by length or by deadline; the
        intermediate per-token events are internal and partition-
        invariant, so the boundaries between completions need no
        stepping. The bound is closed-form (one multiply) while the
        true completion time is a chained sum, so a float-noise
        margin keeps it on the early side: waking a tick early costs
        one no-op step, waking late would break replay identity.
        Either value is None when nothing is scheduled."""
        if not self.healthy:
            return (None, None)
        ge = None
        if self.queue:
            if any(s is None for s in self._slots):
                ge = 0.0  # admission at the next boundary
            else:
                for req in self.queue:
                    if req.deadline_s is None:
                        continue
                    d = req.arrival_s + req.deadline_s
                    if ge is None or d < ge:
                        ge = d
        cover = None
        for slot in self._slots:
            if slot is None:
                continue
            # per-slot decode step: a zoo slot carries its model's
            # TPOT; the .get default keeps unzooed floats identical
            step = slot.get("tpot_s", self.cfg.tpot_s) * self.slowdown
            req = slot["req"]
            if slot["first_s"] is None:
                # prefill event, then >= max(max_new - 1, 1) decodes
                k = max(req.max_new - 1, 1)
                if self.phase == "prefill":
                    # the prefill event itself is terminal for a
                    # prefill-pool replica — no decode tail to bound
                    k = 0
            else:
                k = max(req.max_new - slot["tokens"], 1) - 1
            lb = slot["next_s"] + k * step
            if req.deadline_s is not None:
                # a deadline emission fires at the last in-budget
                # token event, somewhere in (deadline - step,
                # deadline]
                d = req.arrival_s + req.deadline_s - step
                if d < lb:
                    lb = d
            lb -= 1e-9 + 1e-12 * abs(lb)
            if cover is None or lb < cover:
                cover = lb
        return (ge, cover)

    def tick(self, now: float, dt: float) -> List[ReplicaCompletion]:
        """Advance this replica through (now, now + dt]: reap and
        admit at the boundary, then process every scheduled slot
        event inside the window. A call covering no event is a
        strict no-op — the property that lets the event core skip
        the boundaries in between."""
        if not self.healthy:
            return []
        done: List[ReplicaCompletion] = []
        if self.queue:
            # reap queued requests whose deadline passed waiting
            still: List[TraceRequest] = []
            for req in self.queue:
                if (req.deadline_s is not None
                        and now >= req.arrival_s + req.deadline_s):
                    base = (req.request
                            if getattr(req, "is_kv_handoff", False)
                            else req)
                    done.append(ReplicaCompletion(
                        request=base, dispatch_s=now, first_s=None,
                        finish_s=round(
                            req.arrival_s + req.deadline_s, 9),
                        tokens=0, tokens_crc=0,
                        finish_reason="deadline_exceeded"))
                else:
                    still.append(req)
            self.queue = still
            # admit into free slots (tick boundary = chunk boundary)
            for i, slot in enumerate(self._slots):
                if slot is None and self.queue:
                    req = self.queue.pop(0)
                    if getattr(req, "is_kv_handoff", False):
                        # decode-pool admission: the KV arrived
                        # prefilled, so the slot resumes at the
                        # handoff's token count with the next decode
                        # step scheduled from this boundary; the
                        # dispatch/first-token stamps survive the
                        # transfer (TTFT belongs to the request). A
                        # zoo handoff whose model is cold here pays
                        # the weight load before its first step.
                        model = req.request.model
                        swap = self._swap_in(model, now)
                        step = (self._model_tpot.get(
                            model, self.cfg.tpot_s) * self.slowdown)
                        slot = {
                            "req": req.request,
                            "dispatch_s": req.dispatch_s,
                            "next_s": now + swap + step,
                            "first_s": req.first_s,
                            "tokens": req.tokens,
                        }
                        if model and model in self._model_tpot:
                            slot["tpot_s"] = self._model_tpot[model]
                        self._slots[i] = slot
                        continue
                    model = req.model
                    # a cold model's swap precedes its prefill: both
                    # land in the slot's closed-form timeline (zero
                    # on every warm hit and every unzooed run)
                    swap = self._swap_in(model, now)
                    slot = {
                        "req": req,
                        "dispatch_s": now,
                        # absolute time of the slot's next event:
                        # first token at prefill end, then one event
                        # per decoded token
                        "next_s": now + swap + self._prefill_cost(req),
                        "first_s": None,
                        "tokens": 0,
                    }
                    if model and model in self._model_tpot:
                        slot["tpot_s"] = self._model_tpot[model]
                    self._slots[i] = slot
        end = now + dt
        for i, slot in enumerate(self._slots):
            if slot is None or slot["next_s"] > end:
                continue
            tpot = slot.get("tpot_s", self.cfg.tpot_s)
            req = slot["req"]
            deadline = (req.arrival_s + req.deadline_s
                        if req.deadline_s is not None else None)
            while slot["next_s"] <= end:
                t = slot["next_s"]
                if slot["first_s"] is None:
                    # prefill done: the first token lands at t
                    slot["first_s"] = t
                    slot["tokens"] = 1
                    if self.phase == "prefill":
                        # a prefill-pool replica stops here: the
                        # request's KV leaves for the decode pool
                        # (the fleet driver turns this completion
                        # into a LANE_KV_TRANSFER event)
                        done.append(self._complete(
                            slot, finish_s=t,
                            reason="prefill_done"))
                        self._slots[i] = None
                        break
                else:
                    slot["tokens"] += 1
                    if slot["tokens"] >= req.max_new:
                        done.append(self._complete(
                            slot, finish_s=t, reason="length"))
                        self._slots[i] = None
                        break
                # schedule the next token at the CURRENT slowdown;
                # an overshooting deadline fires the moment it is
                # provable, stamped at the deadline itself
                nxt = t + tpot * self.slowdown
                if deadline is not None and nxt > deadline:
                    done.append(self._complete(
                        slot, finish_s=deadline,
                        reason="deadline_exceeded"))
                    self._slots[i] = None
                    break
                slot["next_s"] = nxt
        # a slot that finished mid-tick stays empty until the next
        # tick's admission pass — the chunk-boundary contract
        self._touch()
        return done

    def _complete(self, slot: dict, finish_s: float,
                  reason: str) -> ReplicaCompletion:
        req = slot["req"]
        # audit copies (``~a`` suffix, docs/SDC.md) fingerprint the
        # BASE request so duplicate-compute comparison is apples to
        # apples — a no-op for every other id (retries use ``~r``)
        base_id = req.request_id.split("~a", 1)[0]
        crc = zlib.crc32(repr((base_id, req.seed,
                               slot["tokens"])).encode("utf-8"))
        corrupted = False
        if (self.corrupt_frac > 0.0 and reason == "length"
                and zlib.crc32(
                    f"sdc:{self.replica_id}:{base_id}".encode(
                        "utf-8")) / 2**32 < self.corrupt_frac):
            # the defective chip is fast-but-wrong: timings stand,
            # the fingerprint flips — perturbed by REPLICA identity
            # so two defective chips never agree in error
            crc ^= zlib.crc32(
                f"sdcbits:{self.replica_id}".encode("utf-8"))
            corrupted = True
        return ReplicaCompletion(
            request=req,
            dispatch_s=round(slot["dispatch_s"], 9),
            first_s=(round(slot["first_s"], 9)
                     if slot["first_s"] is not None else None),
            finish_s=round(finish_s, 9),
            tokens=slot["tokens"],
            tokens_crc=crc,
            finish_reason=reason,
            corrupted=corrupted)

    def fail(self, now: float) -> List[TraceRequest]:
        """Preempt this replica: every queued and in-flight request
        is displaced (returned for the router to requeue), the
        prefix cache is lost, and the replica refuses traffic until
        :meth:`restore`."""
        displaced = list(self.queue)
        displaced.extend(s["req"] for s in self._slots
                         if s is not None)
        self.queue = []
        self._slots = [None] * self.cfg.max_slots
        self._prefix_seen.clear()
        self._prefix_owner.clear()
        # the warm pool dies with the replica: restore() brings it
        # back with its configured bring-up model resident
        self.resident_model = self.cfg.resident_model
        self.healthy = False
        self._touch()
        return displaced

    def restore(self, now: float) -> None:
        self.healthy = True
        self._touch()

    def report(self) -> Dict[str, object]:
        out: Dict[str, object] = {
            "kind": "sim",
            "healthy": self.healthy,
            "outstanding": self.outstanding(),
        }
        if self.phase != "unified":
            out["phase"] = self.phase
        if self.slowdown != 1.0:
            out["slowdown"] = round(self.slowdown, 6)
        if self.corrupt_frac:
            out["corrupt_frac"] = round(self.corrupt_frac, 6)
        if self.prefix_hits or self.prefix_misses:
            out["prefix"] = {"hits": self.prefix_hits,
                             "misses": self.prefix_misses}
        if self._model_tpot:
            out["zoo"] = {"resident": self.resident_model,
                          "swaps": self.swaps,
                          "warm_hits": self.warm_hits}
        return out


class EngineReplica:
    """A real ``ServingEngine`` as a fleet replica: one
    ``step_round()`` per tick, completions mapped back to virtual
    time via the engine's (virtual-clock-bound) latency stamps, and
    ``fail()`` driving the engine's slot-failure machinery so the
    chaos scenarios exercise the REAL recovery path."""

    def __init__(self, replica_id: int, engine):
        self.replica_id = replica_id
        self.engine = engine
        self.healthy = True
        # gray slowdown for a REAL engine: we cannot slow the math,
        # so a slowdown of k steps the engine every k-th tick only —
        # the same virtual-time inflation the analytic replica models
        self._stride = 1
        self._tick_no = 0
        self._dispatched: Dict[str, TraceRequest] = {}
        self._dispatch_s: Dict[str, float] = {}

    @property
    def slowdown(self) -> float:
        return float(self._stride)

    def set_slowdown(self, factor: float) -> None:
        self._stride = max(1, int(round(factor)))

    def outstanding(self) -> int:
        return self.engine.outstanding()

    def idle(self) -> bool:
        return self.outstanding() == 0

    def submit(self, req: TraceRequest, now: float) -> bool:
        from kind_tpu_sim.models.serving import (
            EngineSaturated,
            Request,
        )

        if not self.healthy:
            return False
        try:
            self.engine.submit(Request(
                request_id=req.request_id,
                prompt=list(req.prompt),
                max_new=req.max_new,
                seed=req.seed,
                deadline_s=req.deadline_s,
                cache_prefix=req.prefix_group >= 0,
            ))
        except EngineSaturated:
            return False
        self._dispatched[req.request_id] = req
        self._dispatch_s[req.request_id] = now
        return True

    def tick(self, now: float, dt: float) -> List[ReplicaCompletion]:
        if not self.healthy:
            return []
        self._tick_no += 1
        if not self.idle() and self._tick_no % self._stride == 0:
            self.engine.step_round()
        out = []
        for c in self.engine.poll():
            req = self._dispatched.pop(c.request_id)
            disp = self._dispatch_s.pop(c.request_id)
            crc = zlib.crc32(repr(tuple(c.tokens)).encode("utf-8"))
            first = (disp + c.ttft_s if c.ttft_s is not None
                     and c.tokens else None)
            out.append(ReplicaCompletion(
                request=req,
                dispatch_s=round(disp, 9),
                first_s=(round(first, 9)
                         if first is not None else None),
                finish_s=round(disp + (c.e2e_s or 0.0), 9),
                tokens=len(c.tokens),
                tokens_crc=crc,
                finish_reason=c.finish_reason))
        return out

    def cancel(self, request_id: str) -> bool:
        """Hedge cancellation on a real engine: a still-queued
        request is withdrawn cleanly; one already prefilling keeps
        its slot (we cannot unpick a real matmul mid-chunk) and the
        caller dedupes its late completion instead — the same
        drop-the-loser-result contract the worker pool's
        WorkerCancelled read-cancel uses."""
        eng = self.engine
        for i, r in enumerate(eng.queue):
            if r.request_id == request_id:
                del eng.queue[i]
                eng._req_clock.pop(request_id, None)
                self._dispatched.pop(request_id, None)
                self._dispatch_s.pop(request_id, None)
                return True
        return False

    def fail(self, now: float) -> List[TraceRequest]:
        """The real recovery lever: every slot takes
        ``inject_slot_failure`` (mid-stream requests requeue inside
        the engine, uncorrupted by construction), then the engine's
        whole queue is drained back to the router. Quarantine stays
        on until :meth:`restore` lifts it slot by slot."""
        eng = self.engine
        for slot in range(eng.serving.max_slots):
            eng.inject_slot_failure(slot, quarantine=True)
        displaced = []
        for r in eng.queue:
            displaced.append(self._dispatched.pop(r.request_id))
            self._dispatch_s.pop(r.request_id, None)
            # the engine keyed latency clocks by id at submit; drop
            # them so a post-recovery resubmit isn't a duplicate
            eng._req_clock.pop(r.request_id, None)
        eng.queue = []
        self.healthy = False
        return displaced

    def restore(self, now: float) -> None:
        for slot in range(self.engine.serving.max_slots):
            self.engine.restore_slot(slot)
        self.healthy = True

    def report(self) -> Dict[str, object]:
        return {
            "kind": "engine",
            "healthy": self.healthy,
            "outstanding": self.outstanding(),
            "engine": self.engine.report(),
        }


class Router:
    """The fleet's balancing + admission layer.

    Requests land in a bounded central queue; each ``dispatch()``
    pass drains it head-first onto replicas by policy. A request the
    head cannot place (every candidate refuses) blocks the pass —
    FCFS, no overtaking, same as the engine's admission. Expired
    queued requests complete as ``deadline_exceeded`` without ever
    touching a replica; a full central queue sheds on arrival."""

    def __init__(self, replicas: Sequence, policy: str = "round-robin",
                 max_queue: int = 0, affinity_spill: int = 8,
                 health=None, overload=None, disagg: bool = False,
                 tenancy=None, zoo: bool = False):
        if policy not in POLICIES:
            raise ValueError(
                f"unknown policy {policy!r}; known: "
                f"{', '.join(POLICIES)}")
        self.replicas: List = list(replicas)
        self.policy = policy
        self.max_queue = max_queue
        # disaggregated mode (docs/DISAGG.md): arrivals route to the
        # prefill pool, KV handoffs to the decode pool, each pool
        # picked least-outstanding within itself. Handoffs wait in
        # their OWN queue — a blocked prefill head must never starve
        # prefilled work out of the decode pool (that isolation IS
        # the disagg claim), and a handoff is never shed: its prefill
        # compute is already spent.
        self.disagg = disagg
        self.kv_queue: List = []
        self.kv_routed = 0
        self.kv_expired = 0
        # optional fleet.tenancy.TenancyState: with isolation on the
        # central queue drains by QoS-ranked deficit round robin
        # (docs/TENANCY.md) — strict priority across tiers, weighted
        # fair shares within one, FIFO within a tenant — and the KV
        # lane defers handoffs whose tenant is over its decode-pool
        # budget instead of head-blocking everyone behind them
        self.tenancy = tenancy
        # model zoo (docs/ZOO.md): model-named requests route
        # warm-first (replicas whose resident model matches, then by
        # load), a replica that cannot hold the model leaves the
        # candidate set, and a model NO replica can hold sheds loudly
        # instead of head-blocking the queue forever
        self.zoo = zoo
        self.warm_routes = 0
        self.cold_routes = 0
        self._drr_deficit: Dict[str, float] = {}
        self._drr_pos: Dict[int, int] = {}
        self.drr_rounds = 0
        self.kv_deferred = 0
        # optional fleet.overload.OverloadState: per-replica circuit
        # breakers gate the candidate set (an OPEN breaker sheds
        # fast — its replica leaves the ordering until the half-open
        # probe window), and every placement is reported back so the
        # hedging layer can arm its timer (docs/OVERLOAD.md)
        self.overload = overload
        # placement hook: called (request, replica, now) on every
        # successful submit — the fleet driver arms hedge timers and
        # breaker probe accounting through it
        self.on_place = None
        # optional kind_tpu_sim.health.FailureDetector: quarantined
        # replicas leave the candidate set entirely, and the load
        # orderings become LATENCY-AWARE — a replica's queue depth is
        # weighted by its service-time EWMA relative to the fleet
        # baseline, so a slow-but-not-yet-quarantined replica is
        # down-weighted instead of treated as equal capacity
        self.health = health
        # prefix-affinity: preferred replica may be this many
        # requests MORE loaded than the least-loaded one before the
        # router spills the request elsewhere (cache locality is
        # worth a bounded queue imbalance, not an unbounded one)
        self.affinity_spill = affinity_spill
        self.queue: List[TraceRequest] = []
        self._rr = 0
        # columnar mirror (fleet/columnar.py), set by a columnar
        # FleetSim: enables the argmin routing fast path
        self._columns = None
        self.routed = 0
        self.shed = 0
        self.expired_queued = 0
        self.requeues = 0
        self.per_replica: Dict[int, int] = {}
        self.affinity_hits = 0
        self.affinity_spills = 0

    # -- policy ------------------------------------------------------

    def _pool(self, need: str) -> List:
        """The phase-eligible replica set: pool members plus any
        ``unified`` stragglers (a mixed fleet routes everywhere)."""
        if not self.disagg:
            return self.replicas
        return [r for r in self.replicas
                if getattr(r, "phase", "unified") in ("unified", need)]

    def _healthy(self, now: float = 0.0,
                 pool: Optional[List] = None) -> List:
        base = self.replicas if pool is None else pool
        out = [r for r in base if r.healthy]
        if self.health is not None:
            unquarantined = [r for r in out
                             if not self.health.quarantined(
                                 f"replica-{r.replica_id}")]
            # never quarantine the whole fleet out of service: with
            # no clean replica left, degraded capacity beats none
            if unquarantined:
                out = unquarantined
        if self.overload is not None:
            allowed = [r for r in out
                       if self.overload.breaker_allows(
                           f"replica-{r.replica_id}", now)]
            # the same never-empty rule as quarantine: all breakers
            # open means the fleet is collapsing anyway — degraded
            # candidates beat a routing black hole
            if allowed:
                out = allowed
        return out

    def _load_key(self, r) -> float:
        """Effective load for the latency-aware orderings: queue
        depth weighted by the replica's relative service time (1.0
        without a detector or before it has a baseline)."""
        if self.health is None:
            return float(r.outstanding())
        rel = self.health.relative_latency(
            f"replica-{r.replica_id}")
        return (r.outstanding() + 1) * rel

    def _pick_order(self, req: TraceRequest,
                    now: float = 0.0) -> List:
        """Candidate replicas, best first, per policy. Ties break on
        replica_id — determinism over cleverness. In disagg mode the
        candidate set narrows to the request's phase pool FIRST, so
        the health/breaker never-empty fallbacks stay per-pool —
        routing an arrival to a decode replica would silently
        re-unify the fleet."""
        is_handoff = getattr(req, "is_kv_handoff", False)
        pool = self._pool("decode" if is_handoff else "prefill")
        healthy = self._healthy(now, pool)
        if not healthy:
            return []
        model = getattr(req, "model", "") if self.zoo else ""
        if model:
            # model-aware routing (docs/ZOO.md): replicas that can
            # hold the model, warm-resident first, then by load — a
            # warm hit skips the weight-load entirely, so locality
            # outranks every balancing policy for named models
            serving = [r for r in healthy
                       if getattr(r, "can_serve",
                                  lambda m: True)(model)]
            return sorted(
                serving,
                key=lambda r: (
                    0 if getattr(r, "resident_model", "") == model
                    else 1,
                    self._load_key(r), r.replica_id))
        if is_handoff:
            # handoff placement is least-outstanding within the
            # decode pool under every policy: the prefix cohort's
            # locality was already spent at prefill
            return sorted(
                healthy, key=lambda r: (self._load_key(r),
                                        r.replica_id))
        if self.policy == "round-robin":
            start = self._rr % len(healthy)
            return healthy[start:] + healthy[:start]
        by_load = sorted(
            healthy, key=lambda r: (self._load_key(r),
                                    r.replica_id))
        if self.policy == "least-outstanding":
            return by_load
        # prefix-affinity: grouped requests stick to a stable home
        # replica (crc of the group id over the FULL replica list, so
        # the mapping survives scale events for existing groups);
        # ungrouped traffic falls back to least-outstanding
        if req.prefix_group < 0:
            return by_load
        key = zlib.crc32(f"group:{req.prefix_group}".encode("utf-8"))
        home = pool[key % len(pool)]
        if not home.healthy or (
                self.health is not None
                and self.health.quarantined(
                    f"replica-{home.replica_id}")):
            return by_load
        if home not in healthy:
            # filtered out above (e.g. an open breaker): affinity
            # never overrides a tripped breaker
            return by_load
        floor = by_load[0].outstanding()
        if home.outstanding() - floor > self.affinity_spill:
            self.affinity_spills += 1
            return by_load
        self.affinity_hits += 1
        return [home] + [r for r in by_load if r is not home]

    def _fast_pick(self, req: TraceRequest):
        """The columnar routing fast path (fleet/columnar.py): the
        load-ordered policies' first candidate — the healthy replica
        minimizing (outstanding, replica_id) — via one masked argmin
        instead of a full sort. Engages only where the ordering is
        EXACTLY that key: least-outstanding (and prefix-affinity's
        ungrouped fallback), no detector weighting, no breaker
        filtering, no phase pools. Anything else answers None and
        the sorted path runs unchanged; a refused submit also falls
        back to it (refusal mutates nothing, so re-offering to the
        same first candidate is a no-op)."""
        cols = self._columns
        if (cols is None or self.disagg or self.zoo
                or self.health is not None
                or self.overload is not None):
            return None
        if self.policy == "round-robin":
            return None
        if self.policy == "prefix-affinity" and req.prefix_group >= 0:
            return None
        return cols.pick_least_outstanding()

    # -- surface -----------------------------------------------------

    def offer(self, req: TraceRequest,
              now: float) -> Optional[ReplicaCompletion]:
        """Admit one arrival into the central queue; returns a shed
        completion when admission control refuses it."""
        if self.max_queue and len(self.queue) >= self.max_queue:
            self.shed += 1
            metrics.fleet_board().incr("requests_shed")
            metrics.recovery_log().record(
                "fleet_shed", request=req.request_id)
            return ReplicaCompletion(
                request=req, dispatch_s=now, first_s=None,
                finish_s=now, tokens=0, tokens_crc=0,
                finish_reason="shed")
        self.queue.append(req)
        return None

    def offer_handoff(self, handoff) -> None:
        """Admit one delivered KV handoff into the decode lane. No
        admission control here by design: the handoff's prefill
        compute is already spent, so shedding it would burn capacity
        twice — backpressure belongs at the arrival edge."""
        self.kv_queue.append(handoff)

    def requeue_front(self, displaced: Sequence[TraceRequest]) -> None:
        """Displaced requests (a failed replica's) go back to the
        queue HEAD in their original arrival order. A displaced KV
        handoff unwraps to its base request — the KV cache died with
        the replica, so the request re-prefills from scratch."""
        ordered = sorted(
            (r.request if getattr(r, "is_kv_handoff", False) else r
             for r in displaced),
            key=lambda r: (r.arrival_s, r.request_id))
        self.queue[:0] = ordered
        self.requeues += len(ordered)
        metrics.fleet_board().incr("fleet_requeues", len(ordered))

    def dispatch(self, now: float) -> List[ReplicaCompletion]:
        """One placement pass; returns terminal outcomes decided AT
        THE ROUTER (queue-deadline expiries). The KV lane drains
        BEFORE the arrival queue: prefilled work is the most
        expensive work in the system to lose to queueing."""
        out: List[ReplicaCompletion] = []
        if self.kv_queue:
            still_kv: List = []
            for h in self.kv_queue:
                if (h.deadline_s is not None
                        and now >= h.arrival_s + h.deadline_s):
                    self.kv_expired += 1
                    metrics.disagg_board().incr("kv_expired_queued")
                    out.append(ReplicaCompletion(
                        request=h.request, dispatch_s=now,
                        first_s=None,
                        finish_s=round(
                            h.arrival_s + h.deadline_s, 9),
                        tokens=0, tokens_crc=0,
                        finish_reason="deadline_exceeded"))
                else:
                    still_kv.append(h)
            self.kv_queue = still_kv
            if self.tenancy is not None and self.tenancy.isolation:
                self._drain_kv_tenanted(now)
            else:
                while self.kv_queue:
                    h = self.kv_queue[0]
                    if not self._place_handoff(h, now):
                        # head blocks: the decode pool is saturated
                        # (or gone — the disagg-pool-loss scenario);
                        # the handoff waits rather than sheds
                        break
                    self.kv_queue.pop(0)
        still: List[TraceRequest] = []
        for req in self.queue:
            if (req.deadline_s is not None
                    and now >= req.arrival_s + req.deadline_s):
                self.expired_queued += 1
                metrics.fleet_board().incr("deadline_expired_queued")
                out.append(ReplicaCompletion(
                    request=req, dispatch_s=now, first_s=None,
                    finish_s=round(req.arrival_s + req.deadline_s, 9),
                    tokens=0, tokens_crc=0,
                    finish_reason="deadline_exceeded"))
            elif (self.zoo and req.model
                  and not self._servable(req.model)):
                # no replica in the fleet can EVER hold this model
                # (it fits no present generation): shed loudly now
                # rather than head-block FCFS until the heat death
                # of the trace
                self.shed += 1
                metrics.fleet_board().incr("requests_shed")
                metrics.recovery_log().record(
                    "fleet_shed", request=req.request_id)
                out.append(ReplicaCompletion(
                    request=req, dispatch_s=now, first_s=None,
                    finish_s=now, tokens=0, tokens_crc=0,
                    finish_reason="shed"))
            else:
                still.append(req)
        self.queue = still
        if self.tenancy is not None and self.tenancy.isolation:
            self._dispatch_drr(now)
        else:
            while self.queue:
                if not self._try_place(self.queue[0], now):
                    break  # head blocks: FCFS, retry next pass
        return out

    def _try_place(self, req: TraceRequest, now: float) -> bool:
        """One placement attempt (fast path, then the sorted path);
        bookkeeping via :meth:`_note_place` on success. The columnar
        argmin picks WHERE a request lands; which request goes next
        is the queue discipline's call (FCFS or DRR) — the two
        compose, so tenancy never forces the sorted path."""
        fast = self._fast_pick(req)
        if fast is not None and fast.submit(req, now):
            self._note_place(req, fast, now)
            return True
        for replica in self._pick_order(req, now):
            if replica.submit(req, now):
                self._note_place(req, replica, now)
                return True
        return False

    def _dispatch_drr(self, now: float) -> None:
        """Deficit round robin over tenants (docs/TENANCY.md): serve
        the best QoS rank present (strict priority — interactive
        never waits behind batch), rotate tenants within the rank,
        top each visit up by ``quantum x weight`` (capped at 2x so an
        idle tenant banks one round, not history), and serve the
        tenant's FIFO head while credit lasts. A blocked tenant head
        skips to the next tenant instead of head-blocking the rank —
        THE fairness move FCFS cannot make. Deficit resets when a
        tenant's backlog empties (classic DRR, no credit hoarding);
        all state advances only on placements, so replay identity
        holds under any tick partition."""
        ten = self.tenancy
        progress = True
        while progress and self.queue:
            progress = False
            fifos: Dict[str, List[TraceRequest]] = {}
            for req in self.queue:
                fifos.setdefault(tenant_of(req), []).append(req)
            rank = min(ten.qos_rank(n) for n in fifos)
            names = sorted(n for n in fifos
                           if ten.qos_rank(n) == rank)
            pos = self._drr_pos.get(rank, 0) % len(names)
            for name in names[pos:] + names[:pos]:
                fifo = fifos[name]
                topup = ten.drr_quantum * ten.weight(name)
                deficit = min(
                    self._drr_deficit.get(name, 0.0) + topup,
                    2.0 * topup)
                while fifo and deficit >= 1.0:
                    if not self._try_place(fifo[0], now):
                        break
                    fifo.pop(0)
                    deficit -= 1.0
                    progress = True
                self._drr_deficit[name] = (
                    deficit if fifo else 0.0)
            if len(names) > 1:
                self._drr_pos[rank] = (pos + 1) % len(names)
            if progress:
                self.drr_rounds += 1

    def _servable(self, model: str) -> bool:
        """Can ANY replica (healthy or not — an outage is not
        unservability) ever hold this model's weights?"""
        return any(getattr(r, "can_serve", lambda m: True)(model)
                   for r in self.replicas)

    def _place_handoff(self, h, now: float) -> bool:
        """Submit one KV handoff into the decode pool; bookkeeping on
        success (the KV lane's analog of :meth:`_note_place`)."""
        for replica in self._pick_order(h, now):
            if replica.submit(h, now):
                self.kv_routed += 1
                self.per_replica[replica.replica_id] = (
                    self.per_replica.get(
                        replica.replica_id, 0) + 1)
                metrics.disagg_board().incr("kv_handoffs_routed")
                return True
        return False

    def _drain_kv_tenanted(self, now: float) -> None:
        """The KV lane under isolation: a handoff whose tenant is at
        its decode-pool occupancy budget DEFERS (stays queued — its
        prefill is spent, shedding would burn it twice) without
        head-blocking other tenants' handoffs; pool saturation still
        head-blocks everyone, same as the untenanted lane."""
        ten = self.tenancy
        pool = self._pool("decode")
        capacity = self._pool_capacity(pool)
        kept: List = []
        blocked = False
        for h in self.kv_queue:
            if blocked:
                kept.append(h)
                continue
            name = tenant_of(h)
            budget = ten.kv_budget(name, capacity)
            if (budget is not None
                    and self._tenant_pool_load(name, pool)
                    >= budget):
                ten.note_kv_deferred(name)
                self.kv_deferred += 1
                kept.append(h)
                continue
            if not self._place_handoff(h, now):
                kept.append(h)
                blocked = True
        self.kv_queue = kept

    @staticmethod
    def _pool_capacity(pool) -> int:
        """Total concurrency slots across a pool (the KV budget's
        denominator); engine replicas answer via their engine."""
        total = 0
        for r in pool:
            cfg = getattr(r, "cfg", None)
            if cfg is not None and hasattr(cfg, "max_slots"):
                total += cfg.max_slots
            else:
                total += r.engine.serving.max_slots
        return total

    @staticmethod
    def _tenant_pool_load(name: str, pool) -> int:
        """One tenant's current decode-pool occupancy: its requests
        queued at or running on the pool's replicas."""
        n = 0
        for r in pool:
            for req in getattr(r, "queue", ()):
                if tenant_of(req) == name:
                    n += 1
            for slot in getattr(r, "_slots", ()):
                if (slot is not None
                        and tenant_of(slot["req"]) == name):
                    n += 1
        return n

    def _note_place(self, req: TraceRequest, replica,
                    now: float) -> None:
        """Shared bookkeeping for a successful placement (both the
        sorted path and the columnar fast path land here). DRR may
        place from mid-queue; request ids are unique, so remove() is
        unambiguous (and identical to pop(0) for an FCFS head)."""
        self.queue.remove(req)
        self.routed += 1
        self.per_replica[replica.replica_id] = (
            self.per_replica.get(replica.replica_id, 0) + 1)
        metrics.fleet_board().incr("requests_routed")
        if self.zoo and req.model:
            if (getattr(replica, "resident_model", "")
                    == req.model):
                self.warm_routes += 1
            else:
                self.cold_routes += 1
        if self.policy == "round-robin":
            self._rr += 1
        if self.overload is not None:
            self.overload.breaker_dispatch(
                f"replica-{replica.replica_id}")
        if self.on_place is not None:
            self.on_place(req, replica, now)

    def report(self) -> Dict[str, object]:
        out: Dict[str, object] = {
            "policy": self.policy,
            "routed": self.routed,
            "shed": self.shed,
            "expired_queued": self.expired_queued,
            "requeues": self.requeues,
            "queued": len(self.queue),
            "per_replica": {str(k): v for k, v in
                            sorted(self.per_replica.items())},
        }
        if self.policy == "prefix-affinity":
            out["affinity"] = {"hits": self.affinity_hits,
                               "spills": self.affinity_spills}
        if self.tenancy is not None and self.tenancy.isolation:
            out["fair_queue"] = {
                "quantum": round(self.tenancy.drr_quantum, 6),
                "rounds": self.drr_rounds,
            }
        if self.disagg:
            out["kv"] = {"routed": self.kv_routed,
                         "expired": self.kv_expired,
                         "queued": len(self.kv_queue)}
            if self.kv_deferred:
                out["kv"]["deferred"] = self.kv_deferred
        if self.zoo:
            out["zoo"] = {"warm_routes": self.warm_routes,
                          "cold_routes": self.cold_routes}
        return out
