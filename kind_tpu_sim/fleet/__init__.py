"""Deterministic multi-replica serving fleet simulator (docs/FLEET.md).

The layer above ``models/serving.py``: seeded open-loop load
generation (loadgen), SLO-aware routing over N replicas (router),
streaming percentile/attainment/goodput accounting (slo), and
queue/SLO-driven autoscaling with modeled warm-up (autoscaler), all
advanced by one virtual-clock tick loop (sim). Same seed, same
config => byte-identical completion logs and SLO reports.

Knobs: KIND_TPU_SIM_FLEET_SEED (loadgen.resolve_seed),
KIND_TPU_SIM_FLEET_TICK_S (sim.resolve_tick_s),
KIND_TPU_SIM_FLEET_WARMUP_S (autoscaler.resolve_warmup_s),
KIND_TPU_SIM_HEALTH_* (health.DetectorConfig — the gray-failure
detection layer, docs/HEALTH.md), KIND_TPU_SIM_TRAIN_* (the
training tenancy, docs/TRAINING.md), KIND_TPU_SIM_TENANT_* (the
serving multi-tenancy layer, docs/TENANCY.md).
"""

from kind_tpu_sim.health import (  # noqa: F401
    DetectorConfig,
    FailureDetector,
)

from kind_tpu_sim.fleet.autoscaler import (  # noqa: F401
    Autoscaler,
    AutoscalerConfig,
    ScaleEvent,
    resolve_warmup_s,
)
from kind_tpu_sim.fleet.costmodel import (  # noqa: F401
    DEFAULT_GENERATION,
    GENERATION_FACTS,
    GENERATIONS,
    CostModel,
    RequestCost,
    calibrate,
    generation_of_accelerator,
    kv_bytes_per_token,
    load_calibration,
    load_generation,
    parse_geometry,
)
from kind_tpu_sim.fleet.disagg import (  # noqa: F401
    DisaggConfig,
    KvHandoff,
    calibrated_sim_config,
    kv_transfer_s,
    resolve_dtype,
    resolve_tier,
)
from kind_tpu_sim.fleet.events import (  # noqa: F401
    LANE_ARRIVAL,
    LANE_AUTOSCALER,
    LANE_CHAOS,
    LANE_COMPLETION,
    LANE_HEALTH_PROBE,
    LANE_KV_TRANSFER,
    LANE_MODEL_SWAP,
    LANE_PLANNER,
    LANES,
    DueSet,
    EventHeap,
    resolve_event_core,
)
from kind_tpu_sim.fleet.loadgen import (  # noqa: F401
    FLEET_SEED_ENV,
    TraceRequest,
    VirtualClock,
    WorkloadSpec,
    generate_trace,
    load_trace,
    resolve_seed,
    save_trace,
)
from kind_tpu_sim.fleet.overload import (  # noqa: F401
    BrownoutController,
    CircuitBreaker,
    LatencyQuantile,
    OverloadConfig,
    OverloadState,
    TokenBucket,
    request_tier,
    resolve_breaker_window,
    resolve_brownout,
    resolve_hedge_quantile,
    resolve_retry_budget,
    surge_trace,
)
from kind_tpu_sim.fleet.router import (  # noqa: F401
    POLICIES,
    EngineReplica,
    ReplicaCompletion,
    Router,
    SimReplica,
    SimReplicaConfig,
)
from kind_tpu_sim.fleet.sim import (  # noqa: F401
    ChaosEvent,
    FleetConfig,
    FleetSchedConfig,
    FleetSim,
    attainment_over,
    resolve_fast_forward,
    resolve_tick_s,
)
from kind_tpu_sim.fleet.tenancy import (  # noqa: F401
    QOS_TIERS,
    RateBucket,
    TenancyConfig,
    TenancyState,
    TenantSpec,
    default_tenancy,
    generate_tenant_trace,
    resolve_drr_quantum,
    resolve_isolation,
    tenant_of,
    tenant_surge_trace,
)
from kind_tpu_sim.fleet.zoo import (  # noqa: F401
    ModelSpec,
    SwapEvent,
    ZooConfig,
    default_zoo,
    fits,
    model_sim_config,
    placements,
    resolve_generation,
    stamp_models,
    swap_s,
    zoo_config_from_dict,
)
from kind_tpu_sim.fleet.training import (  # noqa: F401
    TRAIN_KINDS,
    TrainingConfig,
    TrainingGang,
    TrainingGangConfig,
    TrainingTenant,
    expected_overhead,
    gang_mesh,
    gangs_from_manifest,
    grow_topology,
    ising_gang,
    optimal_cadence_steps,
    shrink_topology,
    step_time_s,
    to_manifest,
    verify_ledger,
)
from kind_tpu_sim.fleet.slo import (  # noqa: F401
    FixedBucketHistogram,
    SloPolicy,
    SloTracker,
    brute_force_percentile,
)
