"""The global capacity planner: reserved vs spot across zones.

Sits ABOVE the per-cell autoscalers (docs/GLOBE.md). Each cell's
autoscaler still makes the local scale-up/scale-down calls — but its
ceiling is no longer a static config: every cell permanently owns its
**reserved** pool (the autoscaler's ``min_replicas`` floor — capacity
you paid for up front), while a shared **spot/preemptible** budget of
replicas moves between cells as demand does. A cell under backlog
pressure is granted spot replicas (its autoscaler cap rises and the
local autoscaler does the actual scale-up, paying the usual placement
+ warm-up); a cell that has gone quiet hands its grant back once it
has actually shrunk beneath it (reclaim never displaces running
work — spot here is preemptible at the PLANNING tier, not a kill
switch). With follow-the-sun diurnal zones, the budget provably
follows the peak around the planet, which is the whole economic
argument for spot capacity.

Deterministic: cells are evaluated in (pressure, name) order on a
fixed virtual-time cadence; the grant ledger is part of the globe
report and replays byte-identically.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Sequence

from kind_tpu_sim import metrics
from kind_tpu_sim.globe.cell import Cell


@dataclasses.dataclass(frozen=True)
class PlannerConfig:
    # spot replicas shared across every cell (the global chip budget
    # expressed in replica units; chips = replicas x slice size)
    spot_budget: int = 4
    eval_every_s: float = 0.5
    # backlog per routable replica that earns a cell a spot grant
    up_backlog: float = 4.0
    # ... and the calm level below which its grant is reclaimed
    down_backlog: float = 0.5

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


class GlobalPlanner:
    """Pure decision logic over the cells' observable state: the
    globe driver calls :meth:`evaluate` on the cadence; grants are
    enacted by raising/lowering each cell autoscaler's
    ``max_replicas`` cap in place."""

    def __init__(self, cfg: PlannerConfig, cells: Sequence[Cell]):
        self.cfg = cfg
        self.cells = [c for c in cells
                      if c.sim.autoscaler is not None]
        self.grants: Dict[str, int] = {c.name: 0 for c in self.cells}
        self.reserved: Dict[str, int] = {
            c.name: c.sim.autoscaler.cfg.min_replicas
            for c in self.cells}
        # training tenants (docs/TRAINING.md): cells whose fleets
        # run an elastic training tenancy can consume IDLE spot
        # budget as growth rungs — and hand it back (shrink, never
        # abort) when serving pressure wants the budget
        self.train_cells = [c for c in cells
                            if c.sim.trainer is not None]
        self.train_grants: Dict[str, int] = {
            c.name: 0 for c in self.train_cells}
        self.events: List[dict] = []
        # pin every managed cell's starting cap to its reserved
        # floor: capacity beyond it must be granted from the budget
        for cell in self.cells:
            self._set_cap(cell)

    def _set_cap(self, cell: Cell) -> None:
        scaler = cell.sim.autoscaler
        cap = self.reserved[cell.name] + self.grants[cell.name]
        scaler.cfg = dataclasses.replace(scaler.cfg,
                                         max_replicas=cap)

    def budget_left(self) -> int:
        return (self.cfg.spot_budget - sum(self.grants.values())
                - sum(self.train_grants.values()))

    @staticmethod
    def _pressure(cell: Cell) -> float:
        backlog = (len(cell.sim.router.queue) + len(cell.pending)
                   + sum(r.outstanding()
                         for r in cell.sim.replicas if r.healthy))
        return backlog / max(1, cell.routable_replicas())

    def _event(self, now: float, action: str, cell: Cell) -> None:
        ev = {
            "at_s": round(now, 6), "action": action,
            "cell": cell.name,
            "grants": self.grants.get(cell.name, 0),
            "budget_left": self.budget_left()}
        if action.startswith("train_"):
            ev["train_grants"] = self.train_grants[cell.name]
        self.events.append(ev)
        metrics.globe_board().incr(f"planner_{action}s")

    def _settle_training(self, now: float) -> None:
        """Training-rung settlement: a tenant asked to shrink hands
        its rung back only once the shrink actually happened
        (reclaim never aborts — the planner reads the tenant's own
        outstanding-grant count, it does not seize capacity)."""
        for cell in self.train_cells:
            trainer = cell.sim.trainer
            granted = self.train_grants[cell.name]
            if trainer.spot_granted < granted:
                self.train_grants[cell.name] = trainer.spot_granted
                self._event(now, "train_return", cell)

    def evaluate(self, now: float) -> None:
        """One planning pass: settle training rungs, reclaim from
        the calm, then grant to the pressured — reclaim first so a
        budget freed in zone A's evening is grantable in zone B's
        morning within the same pass (the sun does not wait a
        cadence). Serving outranks training: under serving pressure
        with an empty budget, training rungs are reclaimed FIRST
        (the tenant shrinks at its next checkpointed repartition);
        leftover budget after every serving need is met flows to
        elastic training as growth rungs — spot capacity never
        idles while a tenant could use it."""
        self._settle_training(now)
        by_calm = sorted(self.cells,
                         key=lambda c: (self._pressure(c), c.name))
        for cell in by_calm:
            grant = self.grants[cell.name]
            if grant <= 0:
                continue
            # only reclaim capacity the cell has actually vacated:
            # the local autoscaler drains first, the planner takes
            # the replica back after — spot reclaim never displaces
            if (self._pressure(cell) < self.cfg.down_backlog
                    and len(cell.sim.replicas)
                    <= self.reserved[cell.name] + grant - 1):
                self.grants[cell.name] = grant - 1
                self._set_cap(cell)
                self._event(now, "reclaim", cell)
        pressured = [c for c in self.cells if c.alive
                     and self._pressure(c) > self.cfg.up_backlog]
        if pressured and self.budget_left() <= 0:
            # serving wants budget and none is free: pull training
            # rungs back, most-granted tenant first (name tiebreak)
            for cell in sorted(
                    self.train_cells,
                    key=lambda c: (-self.train_grants[c.name],
                                   c.name)):
                if self.train_grants[cell.name] <= 0:
                    continue
                cell.sim.trainer.reclaim_spot(now)
                self._event(now, "train_reclaim", cell)
                break
        for cell in sorted(self.cells,
                           key=lambda c: (-self._pressure(c),
                                          c.name)):
            if self.budget_left() <= 0:
                break
            if not cell.alive:
                continue
            if self._pressure(cell) > self.cfg.up_backlog:
                self.grants[cell.name] += 1
                self._set_cap(cell)
                self._event(now, "grant", cell)
        if not pressured:
            # idle budget flows to elastic training (growth rungs)
            for cell in sorted(self.train_cells,
                               key=lambda c: c.name):
                if self.budget_left() <= 0:
                    break
                if not cell.alive:
                    continue
                trainer = cell.sim.trainer
                if (trainer.wants_spot()
                        and trainer.spot_granted
                        == self.train_grants[cell.name]):
                    trainer.grant_spot(now)
                    self.train_grants[cell.name] += 1
                    self._event(now, "train_grant", cell)

    def active(self) -> bool:
        """Whether a future evaluation could still act — the globe's
        fast-forward must not skip evals that would reclaim."""
        return (any(g > 0 for g in self.grants.values())
                or any(g > 0 for g in self.train_grants.values()))

    def report(self) -> Dict[str, object]:
        out = {
            "spot_budget": self.cfg.spot_budget,
            "budget_left": self.budget_left(),
            "reserved": dict(sorted(self.reserved.items())),
            "grants": dict(sorted(self.grants.items())),
            "events": self.events,
        }
        if self.train_cells:
            out["train_grants"] = dict(
                sorted(self.train_grants.items()))
        return out
