"""One cell of the globe: a sched inventory + fleet sim, embedded.

A **cell** is the unit of deployment the production papers describe
(one cluster of TPU slices behind one regional load balancer): here
it is exactly one :class:`~kind_tpu_sim.fleet.FleetSim` — router,
replicas, optional autoscaler, optionally scheduler-backed placement
on its own zone-labeled inventory (``FleetConfig.sched``) — advanced
tick-by-tick by the globe driver on ONE shared virtual clock instead
of running its own loop. The front door (frontdoor.py) is the only
traffic source: requests arrive with a modeled DCN delivery delay
and join ``pending``; everything after that is the unmodified fleet
data plane, which is the point — the globe composes the existing
layers, it does not reimplement them.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Callable, Dict, List, Optional

from kind_tpu_sim.fleet.events import LANE_ARRIVAL, DueSet, EventHeap
from kind_tpu_sim.fleet.loadgen import TraceRequest, VirtualClock
from kind_tpu_sim.fleet.sim import FleetConfig, FleetSim


@dataclasses.dataclass(frozen=True)
class CellConfig:
    """One cell's identity + its fleet. ``name`` sorts the globe's
    deterministic iteration order; ``zone`` is the correlated
    failure domain the cell dies with under ``zone_loss``."""

    name: str
    zone: str
    fleet: FleetConfig

    def as_dict(self) -> dict:
        return {
            "name": self.name,
            "zone": self.zone,
            "fleet": self.fleet.as_dict(),
        }


class Cell:
    """A fleet sim plus the globe-facing plumbing: a delivery heap
    (requests in DCN flight), the admitted-but-unticked ``pending``
    deque the fleet pops each step, and alive/draining flags the
    front door consults."""

    def __init__(self, cfg: CellConfig, clock: VirtualClock,
                 on_complete: Optional[Callable] = None):
        self.cfg = cfg
        self.name = cfg.name
        self.zone = cfg.zone
        self.sim = FleetSim(cfg.fleet, trace=[], clock=clock)
        if on_complete is not None:
            self.sim.on_complete = on_complete
        self.pending: deque = deque()
        # requests in DCN flight, on the deterministic event heap
        # (fleet/events.py): (deliver_s, ARRIVAL lane, seq, request)
        # — seq is admission order, the tiebreak for same-tick
        # deliveries
        self.delivery = EventHeap()
        # hedge-cancelled request ids still in DCN flight: the heap
        # has no removal, so cancellation is lazy — the id is
        # dropped when its delivery pops (docs/OVERLOAD.md)
        self._cancelled: set = set()
        self.alive = True
        self.draining = False
        self.peak_outstanding = 0

    # -- capacity / load (the front door's scoring inputs) -----------

    def routable_replicas(self) -> int:
        return sum(1 for r in self.sim.router.replicas if r.healthy)

    def capacity(self) -> int:
        """Concurrent service slots across routable replicas — the
        unit the front door's admission bounds are denominated in."""
        slots = getattr(self.sim.cfg.sim, "max_slots", 1)
        return self.routable_replicas() * slots

    def outstanding(self) -> int:
        """Everything the cell owes: queued at the router, in flight
        on replicas, admitted but unticked, still in DCN flight, and
        (phase-split cells) riding the KV lane between the pools."""
        return (len(self.sim.router.queue)
                + sum(r.outstanding()
                      for r in self.sim.replicas if r.healthy)
                + len(self.pending) + len(self.delivery)
                + len(self.sim.router.kv_queue)
                + len(self.sim._kv_heap))

    def routable(self) -> bool:
        return (self.alive and not self.draining
                and self.routable_replicas() > 0)

    # -- model zoo (docs/ZOO.md) --------------------------------------

    def serves(self, model: str) -> bool:
        """Whether any healthy replica here can run ``model`` at all
        (it fits the replica's generation HBM). Vacuously true for
        unzooed traffic."""
        if not model:
            return True
        return any(
            getattr(r, "can_serve", lambda m: True)(model)
            for r in self.sim.replicas if r.healthy)

    def models_warm(self) -> set:
        """Models resident (weights loaded) on at least one healthy
        replica — the front door's warm-cell spill signal."""
        return {r.resident_model
                for r in self.sim.replicas
                if r.healthy and getattr(r, "resident_model", "")}

    # -- the globe driver's surface ----------------------------------

    def admit(self, req: TraceRequest, deliver_s: float) -> None:
        self.delivery.push(deliver_s, LANE_ARRIVAL, req)
        self.peak_outstanding = max(self.peak_outstanding,
                                    self.outstanding())

    def deliver_due(self, now: float) -> None:
        for req in self.delivery.pop_due(now):
            if self._cancelled:
                if req.request_id in self._cancelled:
                    self._cancelled.discard(req.request_id)
                    continue
            self.pending.append(req)

    def cancel(self, request_id: str) -> bool:
        """First-completion-wins cancellation across the cell (the
        globe hedging layer's lever, docs/OVERLOAD.md): withdraw the
        request wherever it is — admitted-but-unticked, queued at
        the router, or mid-stream on a replica; one still in DCN
        flight cancels lazily at delivery. Returns False when the
        request is nowhere here (already completed) so the caller
        dedupes the late completion instead."""
        for i, req in enumerate(self.pending):
            if req.request_id == request_id:
                del self.pending[i]
                return True
        queue = self.sim.router.queue
        for i, req in enumerate(queue):
            if req.request_id == request_id:
                del queue[i]
                return True
        for replica in self.sim.replicas:
            if (hasattr(replica, "cancel")
                    and replica.cancel(request_id)):
                return True
        kv_queue = self.sim.router.kv_queue
        for i, handoff in enumerate(kv_queue):
            if handoff.request.request_id == request_id:
                del kv_queue[i]
                self.sim._prefill_done_ids.discard(request_id)
                return True
        for entry in self.sim._kv_heap._heap:
            # a KV transfer on the wire cancels lazily, like a
            # request in DCN flight: the sim drops it at delivery
            if entry[3].request.request_id == request_id:
                self.sim._kv_cancelled.add(request_id)
                return True
        for entry in self.delivery._heap:
            if entry[3].request_id == request_id:
                self._cancelled.add(request_id)
                return True
        return False

    def warm_prefix(self, group: int) -> None:
        """Pre-warm one prefix-cache group on every replica (the
        cross-cell failover warm-up, docs/OVERLOAD.md)."""
        for replica in self.sim.replicas:
            if hasattr(replica, "warm_prefix"):
                replica.warm_prefix(group)

    def step(self, now: float, tick: float) -> None:
        if self.alive:
            self.sim.step(now, tick, self.pending)

    def quiescent(self) -> bool:
        return (not self.pending and not self.delivery
                and self.sim.quiescent(self.pending))

    def idle_gap(self) -> bool:
        """Nothing due on this cell before external input arrives —
        the per-cell leg of the globe's fast-forward test."""
        if self.pending or self.delivery:
            return False
        if not self.alive:
            # a dead cell is inert by construction (its load was
            # displaced at failure; it is not stepped)
            return True
        return self.sim._idle_gap(self.pending)

    def event_due(self) -> DueSet:
        """The event core's per-cell leg (docs/PERFORMANCE.md "The
        event core"): delivered-but-unticked work needs the next
        boundary; in-DCN-flight requests apply at their delivery
        instants; everything inside the fleet answers through the
        fleet's own wake computation. A dead cell is inert."""
        due = DueSet()
        if not self.alive:
            return due
        if self.pending:
            return due.need_now()
        due.at(self.delivery.peek_time())
        return due.merge(self.sim._next_wake(self.pending))

    # -- blast-radius chaos ------------------------------------------

    def fail(self, now: float) -> List[TraceRequest]:
        """Zone loss / herd failover hits this cell: every queued,
        in-flight, admitted, and in-DCN-flight request is displaced
        back to the front door; replicas go unhealthy until
        :meth:`restore`."""
        displaced: List[TraceRequest] = []
        if self.sim.trainer is not None:
            # training gangs ride the PreemptionGuard contract
            # (docs/TRAINING.md): checkpoint at the current step,
            # evict, requeue — they rebind when the cell returns,
            # with zero counted steps lost
            self.sim._now = now
            self.sim.trainer.evict_all(now, reason="cell failed")
        for replica in self.sim.replicas:
            if replica.healthy:
                for req in replica.fail(now):
                    # a decode replica's queue may hold KV handoffs:
                    # the front door re-admits TraceRequests, so
                    # unwrap to the base request (full re-prefill on
                    # the failover cell)
                    base = (req.request
                            if getattr(req, "is_kv_handoff", False)
                            else req)
                    self.sim._prefill_done_ids.discard(
                        base.request_id)
                    displaced.append(base)
        displaced.extend(self.sim.displace_disagg())
        displaced.extend(self.sim.router.queue)
        self.sim.router.queue = []
        displaced.extend(self.pending)
        self.pending.clear()
        for req in self.delivery.pop_due(float("inf")):
            if req.request_id in self._cancelled:
                self._cancelled.discard(req.request_id)
                continue
            displaced.append(req)
        self.alive = False
        return displaced

    def restore(self, now: float) -> None:
        for replica in self.sim.replicas:
            if not replica.healthy:
                replica.restore(now)
        self.alive = True

    def report(self) -> Dict[str, object]:
        """Per-cell board for the globe report: the cell's own SLO
        view, router counters, and replica health — everything but
        the per-request log (the globe's global log carries that)."""
        out: Dict[str, object] = {
            "zone": self.zone,
            "alive": self.alive,
            "draining": self.draining,
            "replicas": len(self.sim.replicas),
            "routable_replicas": self.routable_replicas(),
            "peak_outstanding": self.peak_outstanding,
            "slo": self.sim.tracker.report(),
            "router": self.sim.router.report(),
        }
        if self.sim.autoscaler is not None:
            out["autoscaler"] = self.sim.autoscaler.report()
        if self.sim.overload is not None:
            out["overload"] = self.sim.overload.report()
        if self.sim.sched is not None:
            out["sched_event_counts"] = \
                self.sim.sched.report()["event_counts"]
        if self.sim.trainer is not None:
            out["training"] = self.sim.trainer.report()
        if self.sim._zoo is not None:
            out["zoo"] = {
                "generation": (self.sim._gen_of(0)
                               if self.sim._generations else None),
                "warm": sorted(self.models_warm()),
                "swaps": len(self.sim._swap_log),
            }
        return out
