"""The global front door: anycast-style admission over N cells.

The planet-facing half of the globe layer (docs/GLOBE.md). Every
request originates in a zone and hits the front door at its arrival
tick; the front door picks a cell the way a global load balancer
does — **nearest healthy cell first** (DCN round-trip to the cell's
zone is the leading cost), **capacity-aware** (a cell's queue depth,
normalized by its routable slots, is the second), with **sticky
prefix-affinity** (a shared-prefix cohort keeps one home cell so the
cell-level prefix caches stay warm) and **spill** when the preferred
cell is saturated or breaching its SLO window.

Two bounds make the spill safe instead of a cascade amplifier:

* the **nominal depth** (``queue_depth`` × slots) past which a cell
  stops being anyone's first choice, and
* the **hard limit** (nominal × (1 + ``spill_headroom``)) past which
  the front door refuses to admit AT ALL — a surviving cell can
  never be flooded beyond its configured headroom by a thundering
  herd; overflow waits in the front door's own FCFS queue (admission
  control at the planet tier, the same move the router makes at the
  cell tier) and sheds loudly only past ``max_queue``.

Determinism: candidate order is (saturation, cost, cell name); the
affinity map hashes the group id over the STATIC cell list; no
entropy anywhere — same seed, same admissions, byte-identical
reports.
"""

from __future__ import annotations

import dataclasses
import math
import zlib
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence

from kind_tpu_sim import metrics
from kind_tpu_sim.fleet.loadgen import TraceRequest
from kind_tpu_sim.globe.cell import Cell


@dataclasses.dataclass(frozen=True)
class FrontDoorConfig:
    # requests per routable slot a cell absorbs before it stops
    # being a first choice (the saturation-spill trigger)
    queue_depth: float = 4.0
    # extra fraction over nominal depth a cell will accept from
    # spill before the front door refuses outright — the herd bound
    spill_headroom: float = 0.5
    # how many requests MORE loaded (absolute) a cohort's home cell
    # may be than the best candidate before affinity yields
    affinity_spill: int = 8
    # spill away from a cell whose recent SLO window drops below
    # this attainment (None = saturation-only spill)
    slo_spill_below: Optional[float] = 0.7
    slo_window: int = 32
    # cost weight of one unit of normalized load vs one second of
    # DCN round-trip (0.01 ~ "10 ms of latency buys one queue slot
    # per slot of capacity")
    load_weight_s: float = 0.01
    # front-door FCFS overflow queue bound; beyond it, shed loudly
    max_queue: int = 4096
    # shed records kept for inspection (the TOTAL is a counter —
    # a 1M-request surge run must not hold every shed request alive)
    shed_window: int = 64
    # cross-cell prefix-cache warm-up on failover (docs/OVERLOAD.md):
    # when a cohort's home cell stops being routable, the cell now
    # serving the cohort pre-warms its prefix group so post-failover
    # TTFT recovers faster than a cold spill
    warm_on_failover: bool = True

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


class FrontDoor:
    """Admission + cross-cell spill. ``rtt_s(origin_zone, zone)`` is
    the globe's DCN latency model (degraded links inflate it, which
    is how a browned-out path steers traffic away)."""

    def __init__(self, cfg: FrontDoorConfig, cells: Sequence[Cell],
                 rtt_s: Callable[[str, str], float],
                 overload=None):
        self.cfg = cfg
        self.cells = list(cells)          # static: affinity keyspace
        self.rtt_s = rtt_s
        # optional fleet.overload.OverloadState: per-CELL circuit
        # breakers gate the candidate set (the cell tier of the
        # breaker ladder, docs/OVERLOAD.md) and note_result feeds
        # them each completion's SLO verdict
        self.overload = overload
        # admission hook: called (request, origin, cell, now) on
        # every admit — the globe driver arms cross-cell hedge
        # timers through it
        self.on_admit = None
        self.queue: deque = deque()       # (request, origin_zone)
        self.routed = 0
        self.spilled = 0
        self.affinity_hits = 0
        # bounded recent window + exact total: long shed-heavy soaks
        # must not hold every shed request alive
        self.shed: deque = deque(maxlen=max(1, cfg.shed_window))
        self.shed_total = 0
        self.readmitted = 0
        self.prefix_warmups = 0
        self._warmed: set = set()         # (cell, group) once each
        self._slo_window: Dict[str, deque] = {
            c.name: deque(maxlen=cfg.slo_window) for c in cells}

    # -- scoring ------------------------------------------------------

    def _nominal(self, cell: Cell) -> float:
        return max(1.0, cell.capacity() * self.cfg.queue_depth)

    def _hard_limit(self, cell: Cell) -> float:
        return math.ceil(
            self._nominal(cell) * (1.0 + self.cfg.spill_headroom))

    def _slo_breaching(self, cell: Cell) -> bool:
        if self.cfg.slo_spill_below is None:
            return False
        window = self._slo_window[cell.name]
        if len(window) < window.maxlen // 2:
            return False
        return (sum(window) / len(window)
                < self.cfg.slo_spill_below)

    def note_result(self, cell_name: str, slo_ok: bool,
                    now: float = 0.0) -> None:
        """The globe streams every completion's SLO verdict back so
        spill can react to a breaching cell before its queue shows
        it (slow-but-alive cells fill slowly); the same verdict
        feeds the cell's circuit breaker when overload containment
        is on."""
        window = self._slo_window.get(cell_name)
        if window is not None:
            window.append(1 if slo_ok else 0)
        if self.overload is not None:
            self.overload.breaker_record(cell_name, slo_ok, now)

    def _candidates(self, origin: str, now: float = 0.0,
                    model: str = "") -> List[Cell]:
        """Routable cells under their hard limit, best first:
        unsaturated before saturated, then (zoo traffic) cells with
        the model WARM before cells that would cold-swap, then
        DCN-latency + load cost, then name — a pure function of
        (origin, cell states). A model-stamped request only
        considers cells that can serve the model at all (it fits
        some healthy replica's generation); with no model the cold
        bit is constant and the order is the historical one. An
        OPEN per-cell breaker removes its cell from the set (shed
        fast) unless every breaker is open — degraded candidates
        beat a global black hole, the same never-empty rule the
        fleet router applies to quarantine."""
        scored = []
        for cell in self.cells:
            if not cell.routable():
                continue
            if model and not cell.serves(model):
                continue
            load = cell.outstanding()
            if load >= self._hard_limit(cell):
                continue  # the herd bound: never flood past headroom
            saturated = (load >= self._nominal(cell)
                         or self._slo_breaching(cell))
            cold = (1 if model
                    and model not in cell.models_warm() else 0)
            cost = (self.rtt_s(origin, cell.zone)
                    + self.cfg.load_weight_s
                    * load / max(1, cell.capacity()))
            scored.append((1 if saturated else 0, cold, cost,
                           cell.name, cell))
        scored.sort(key=lambda t: t[:4])
        out = [t[4] for t in scored]
        if self.overload is not None:
            allowed = [c for c in out
                       if self.overload.breaker_allows(c.name, now)]
            if allowed:
                out = allowed
        return out

    def _home(self, req: TraceRequest) -> Optional[Cell]:
        """Sticky prefix-affinity: the cohort's home cell, hashed
        over the static cell list so the mapping survives cell
        failures (a dead home just spills until it returns)."""
        if req.prefix_group < 0 or not self.cells:
            return None
        key = zlib.crc32(
            f"globe-group:{req.prefix_group}".encode("utf-8"))
        return self.cells[key % len(self.cells)]

    def pick(self, req: TraceRequest, origin: str,
             now: float = 0.0) -> Optional[Cell]:
        model = getattr(req, "model", "")
        candidates = self._candidates(origin, now, model)
        if not candidates:
            return None
        chosen = candidates[0]
        home = self._home(req)
        if home is not None and home in candidates:
            floor = min(c.outstanding() for c in candidates)
            if home.outstanding() - floor <= self.cfg.affinity_spill:
                self.affinity_hits += 1
                metrics.globe_board().incr("affinity_hits")
                chosen = home
        if model:
            # warm-cell spill accounting (docs/ZOO.md) — zoo traffic
            # only, so unzooed boards keep their historical bytes
            metrics.zoo_board().incr(
                "warm_cell_picks"
                if model in chosen.models_warm()
                else "cold_cell_picks")
        return chosen

    # -- admission ----------------------------------------------------

    def offer(self, req: TraceRequest, origin: str, now: float,
              readmit: bool = False) -> Optional[tuple]:
        """Route one request (or queue it when every cell is at its
        bound). Returns a shed marker tuple only when even the
        front-door queue is full — the caller records it."""
        cell = self.pick(req, origin, now)
        if cell is not None:
            self._admit(cell, req, origin, now, readmit)
            return None
        if len(self.queue) < self.cfg.max_queue:
            self.queue.append((req, origin))
            metrics.globe_board().incr("frontdoor_queued")
            return None
        metrics.globe_board().incr("frontdoor_shed")
        self.shed_total += 1
        self.shed.append((req.request_id, origin, round(now, 6)))
        return (req, origin, now)

    def _warm_failover(self, cell: Cell, req: TraceRequest) -> None:
        """Cross-cell prefix-cache warm-up (docs/OVERLOAD.md): the
        cohort's home cell stopped being routable, so the cell now
        serving it pre-warms the cohort's prefix group — once per
        (cell, group) — and the first post-failover request of the
        cohort prefills suffix-only instead of cold."""
        if not self.cfg.warm_on_failover or req.prefix_group < 0:
            return
        home = self._home(req)
        if home is None or home is cell or home.routable():
            return
        key = (cell.name, req.prefix_group)
        if key in self._warmed:
            return
        self._warmed.add(key)
        cell.warm_prefix(req.prefix_group)
        self.prefix_warmups += 1
        metrics.globe_board().incr("prefix_warmups")

    def _admit(self, cell: Cell, req: TraceRequest, origin: str,
               now: float, readmit: bool) -> None:
        self._warm_failover(cell, req)
        # the full DCN round trip rides on the delivery time, so
        # every latency the cell later measures for this request
        # already includes the network the front door chose
        cell.admit(req, now + self.rtt_s(origin, cell.zone))
        self.routed += 1
        if origin == cell.zone:
            metrics.globe_board().incr("admit_local")
        else:
            self.spilled += 1
            metrics.globe_board().incr("admit_spill")
        if readmit:
            self.readmitted += 1
            metrics.globe_board().incr("herd_readmissions")
        if self.overload is not None:
            self.overload.breaker_dispatch(cell.name)
        if self.on_admit is not None:
            self.on_admit(req, origin, cell, now)

    def pump(self, now: float) -> None:
        """Retry the FCFS overflow queue head-first; the head
        blocking keeps global admission fair, same as the cell
        router's dispatch."""
        while self.queue:
            req, origin = self.queue[0]
            cell = self.pick(req, origin, now)
            if cell is None:
                return
            self.queue.popleft()
            self._admit(cell, req, origin, now, readmit=False)

    def report(self) -> Dict[str, object]:
        out: Dict[str, object] = {
            "routed": self.routed,
            "spilled": self.spilled,
            "affinity_hits": self.affinity_hits,
            "readmitted": self.readmitted,
            "queued": len(self.queue),
            "shed": self.shed_total,
            "hard_limits": {
                c.name: self._hard_limit(c) for c in self.cells},
            "peak_outstanding": {
                c.name: c.peak_outstanding for c in self.cells},
        }
        if self.prefix_warmups:
            out["prefix_warmups"] = self.prefix_warmups
        return out
