"""The globe driver: zones of cells behind one front door.

The fleet-of-fleets (docs/GLOBE.md): per-zone seeded demand (with
optional follow-the-sun diurnal phase offsets) arrives at the front
door, which admits each request to a cell — nearest healthy first,
capacity-aware, spill-bounded; every cell is a full
:class:`~kind_tpu_sim.fleet.FleetSim` (optionally scheduler-backed on
its own zone-labeled inventory) stepped in lockstep on ONE shared
virtual clock; a global capacity planner moves a spot-replica budget
between the cells' autoscalers as the sun moves the load.

Chaos grows the **blast-radius tier** here: ``zone_loss`` kills every
cell in a zone (their whole load re-enters the front door and spills
cross-zone), ``herd_failover`` is the same failure under peak burst
(the spill bound is what keeps it from cascading), ``dcn_degrade``
browns out a zone's inter-zone links (the tier-parameterized ring
cost model from parallel/collectives.py sets the inflation), and
``cell_drain`` is planned maintenance. Per-zone SLO boards prove
containment: a fault's damage must stay inside its failure domain.

Determinism: everything is a pure function of (config, seed) —
per-zone traces derive sub-seeds from ``KIND_TPU_SIM_GLOBE_SEED``,
cells iterate in name order, the front door scores without entropy —
so `globe run --seed 7` twice emits byte-identical reports.
"""

from __future__ import annotations

import dataclasses
import json
import zlib
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

from kind_tpu_sim import metrics
from kind_tpu_sim.analysis import knobs
from kind_tpu_sim.parallel import collectives
from kind_tpu_sim.fleet.autoscaler import AutoscalerConfig
from kind_tpu_sim.fleet.loadgen import (
    TraceRequest,
    VirtualClock,
    WorkloadSpec,
    generate_trace,
)
from kind_tpu_sim.fleet.events import (
    LANE_ARRIVAL,
    LANE_COMPLETION,
    DueSet,
    EventHeap,
    resolve_event_core,
)
from kind_tpu_sim.fleet.overload import OverloadConfig, OverloadState
from kind_tpu_sim.fleet.router import SimReplicaConfig
from kind_tpu_sim.fleet.sim import (
    FleetConfig,
    FleetSchedConfig,
    resolve_fast_forward,
    resolve_tick_s,
)
from kind_tpu_sim.fleet.tenancy import (
    TenancyConfig,
    TenancyState,
    tenant_of,
)
from kind_tpu_sim.fleet.training import TrainingConfig
from kind_tpu_sim.fleet.slo import SloPolicy, SloTracker
from kind_tpu_sim.globe.cell import Cell, CellConfig
from kind_tpu_sim.globe.frontdoor import FrontDoor, FrontDoorConfig
from kind_tpu_sim.globe.planner import GlobalPlanner, PlannerConfig

GLOBE_SEED_ENV = knobs.GLOBE_SEED

GLOBE_CHAOS_ACTIONS = (
    "zone_loss", "zone_restore", "herd_failover",
    "dcn_degrade", "dcn_restore", "cell_drain", "cell_undrain",
)


def resolve_seed(seed: Optional[int] = None) -> int:
    """Explicit seed > env (KIND_TPU_SIM_GLOBE_SEED) > 0."""
    if seed is not None:
        return int(seed)
    return int(knobs.get(GLOBE_SEED_ENV))


@dataclasses.dataclass(frozen=True)
class GlobeWorkloadSpec:
    """Per-zone demand. With ``follow_the_sun`` and a diurnal
    process, zone i's rate profile is phase-shifted by i/len(zones)
    of a period — the staggered peaks the planner's spot budget
    chases."""

    process: str = "poisson"
    rps: float = 40.0
    n_per_zone: int = 200
    prompt_len: Tuple[int, int] = (8, 24)
    max_new: Tuple[int, int] = (4, 12)
    shared_prefix_frac: float = 0.0
    prefix_groups: int = 4
    deadline_s: Optional[float] = None
    diurnal_period_s: float = 20.0
    follow_the_sun: bool = True

    def as_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["prompt_len"] = list(self.prompt_len)
        d["max_new"] = list(self.max_new)
        return d


@dataclasses.dataclass(frozen=True)
class GlobeChaosEvent:
    """One blast-radius fault. ``target`` names a zone (``zone_*``,
    ``herd_failover``, ``dcn_*``) or a cell (``cell_*``); ``param``
    is the DCN link bandwidth factor for ``dcn_degrade``."""

    at_s: float
    action: str
    target: str
    param: float = 0.0

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass(frozen=True)
class GlobeConfig:
    zones: Tuple[str, ...] = ("zone-a", "zone-b", "zone-c")
    cells_per_zone: int = 1
    replicas_per_cell: int = 2
    policy: str = "least-outstanding"   # per-cell router policy
    tick_s: Optional[float] = None
    max_virtual_s: float = 600.0
    sim: SimReplicaConfig = SimReplicaConfig()
    slo: SloPolicy = SloPolicy(ttft_s=1.0, e2e_s=5.0)
    # scheduler-backed cells: each cell's replicas are gangs on its
    # own zone-labeled inventory (FleetConfig.sched, docs/SCHED.md)
    sched: bool = True
    sched_policy: str = "ici"
    # inventory shape of every cell's scheduler (None keeps the
    # FleetSchedConfig default of one 4x8 pod) — a training cell
    # needs headroom beyond serving for the elastic ladder to have
    # anything to scavenge (docs/TRAINING.md)
    cell_pods: Optional[Tuple] = None
    autoscale: bool = False
    autoscaler: AutoscalerConfig = AutoscalerConfig()
    frontdoor: FrontDoorConfig = FrontDoorConfig()
    planner: Optional[PlannerConfig] = None
    # overload containment (docs/OVERLOAD.md): per-origin client
    # retry budgets and cross-cell hedging live at the FRONT DOOR
    # (the client tier); the embedded cells inherit breakers and
    # brownout but never their own retries/hedges — two stacked
    # retry loops would be an amplifier of their own
    overload: Optional[OverloadConfig] = None
    # training tenancy (docs/TRAINING.md): the named cells run this
    # TrainingConfig co-scheduled under their serving fleet (strict
    # priority); empty training_cells defaults to the first cell.
    # Requires scheduler-backed cells (sched=True).
    training: Optional[TrainingConfig] = None
    training_cells: Tuple[str, ...] = ()
    # multi-tenancy (docs/TENANCY.md): per-zone traces draw the
    # tenant/user model, quotas are charged ONCE at the front door
    # (cells inherit the tenancy minus quotas — weighted-fair
    # queuing and KV budgets, no double metering)
    tenancy: Optional[TenancyConfig] = None
    # model zoo (docs/ZOO.md): a ZooConfig stamps every zone trace
    # with model names (fresh crc32 stream — zoo-off traces keep
    # their bytes) and turns on warm-cell spill at the front door
    zoo: Optional[object] = None
    # heterogeneous cells (docs/ZOO.md): accelerator generation
    # names cycled over cells in name order — scheduler-backed
    # cells request the generation's accelerator label (the
    # FleetSchedConfig.replica_accelerator path), analytic cells
    # price its calibration directly. None keeps historical bytes.
    generations: Optional[Tuple[str, ...]] = None
    workload: GlobeWorkloadSpec = GlobeWorkloadSpec()
    # one-way DCN latency unit between adjacent zones; zone pairs
    # farther apart in the zone list cost proportionally more
    dcn_base_s: float = 0.01
    intra_zone_s: float = 0.0005
    # contractlint: ok(drift) -- execution strategy: ff-on vs ff-off reports must diff clean
    fast_forward: Optional[bool] = None
    # event-heap core (None -> resolve_event_core(), default on) —
    # an execution strategy like fast_forward: byte-identical on or
    # off, so it stays OUT of as_dict()
    # contractlint: ok(drift) -- execution strategy: heap-core on vs off reports must diff clean
    event_core: Optional[bool] = None

    def cell_names(self) -> List[str]:
        return [f"{z}/c{i}" for z in self.zones
                for i in range(self.cells_per_zone)]

    def resolve_training_cells(self) -> List[str]:
        """The cells that host the training tenancy: the explicit
        list, or the first cell when training is set and no list is
        given."""
        if self.training is None:
            return []
        if self.training_cells:
            names = set(self.cell_names())
            unknown = [c for c in self.training_cells
                       if c not in names]
            if unknown:
                raise ValueError(
                    f"training_cells {unknown} not in "
                    f"{sorted(names)}")
            return list(self.training_cells)
        return self.cell_names()[:1]

    def as_dict(self) -> dict:
        out = {
            "zones": list(self.zones),
            "cells_per_zone": self.cells_per_zone,
            "replicas_per_cell": self.replicas_per_cell,
            "policy": self.policy,
            "tick_s": resolve_tick_s(self.tick_s),
            "max_virtual_s": self.max_virtual_s,
            "sim": self.sim.as_dict(),
            "slo": {k: v for k, v in
                    dataclasses.asdict(self.slo).items()
                    if v is not None},
            "sched": (self.sched_policy if self.sched else None),
            "cell_pods": ([list(p) for p in self.cell_pods]
                          if self.cell_pods is not None else None),
            "autoscale": self.autoscale,
            "autoscaler": (dataclasses.asdict(self.autoscaler)
                           if self.autoscale else None),
            "frontdoor": self.frontdoor.as_dict(),
            "planner": (self.planner.as_dict()
                        if self.planner is not None else None),
            "workload": self.workload.as_dict(),
            "dcn_base_s": self.dcn_base_s,
            "intra_zone_s": self.intra_zone_s,
        }
        if self.overload is not None:
            out["overload"] = self.overload.as_dict()
        if self.tenancy is not None:
            out["tenancy"] = self.tenancy.as_dict()
        if self.training is not None:
            out["training"] = self.training.as_dict()
            out["training_cells"] = sorted(
                self.resolve_training_cells())
        if self.zoo is not None:
            out["zoo"] = self.zoo.as_dict()
        if self.generations is not None:
            out["generations"] = list(self.generations)
        return out


# -- per-zone traffic --------------------------------------------------


def zone_seed(seed: int, zone: str) -> int:
    """Each zone's private loadgen stream, derived from the globe
    seed — the ChaosSchedule recipe, so zone traffic identity is
    exactly (seed, zone) identity."""
    return zlib.crc32(f"globe:{seed}:{zone}".encode("utf-8"))


def generate_globe_traces(
        cfg: GlobeConfig,
        seed: Optional[int] = None) -> Dict[str, List[TraceRequest]]:
    """One seeded trace per zone; request ids are zone-prefixed so
    they stay unique in the global completion log. Diurnal zones get
    follow-the-sun phase offsets (zone i peaks i/len of a period
    later)."""
    seed = resolve_seed(seed)
    w = cfg.workload
    out: Dict[str, List[TraceRequest]] = {}
    for i, zone in enumerate(cfg.zones):
        phase = 0.0
        if (w.follow_the_sun and w.process == "diurnal"
                and len(cfg.zones) > 1):
            phase = round(
                i * w.diurnal_period_s / len(cfg.zones), 6)
        spec = WorkloadSpec(
            process=w.process, rps=w.rps,
            n_requests=w.n_per_zone,
            prompt_len=w.prompt_len, max_new=w.max_new,
            shared_prefix_frac=w.shared_prefix_frac,
            prefix_groups=w.prefix_groups,
            deadline_s=w.deadline_s,
            diurnal_period_s=w.diurnal_period_s,
            phase_s=phase,
            tenancy=cfg.tenancy,
            zoo=cfg.zoo)
        out[zone] = [
            dataclasses.replace(r,
                                request_id=f"{zone}/{r.request_id}")
            for r in generate_trace(spec, zone_seed(seed, zone))]
    return out


def save_globe_trace(path: str,
                     traces: Dict[str, List[TraceRequest]]) -> None:
    """One JSON object per line with the origin zone riding along —
    byte-stable (sorted keys, zone then arrival order)."""
    with open(path, "w", encoding="utf-8") as fh:
        for zone in sorted(traces):
            for req in traces[zone]:
                d = req.as_dict()
                d["origin"] = zone
                fh.write(json.dumps(d, sort_keys=True))
                fh.write("\n")


def load_globe_trace(path: str) -> Dict[str, List[TraceRequest]]:
    out: Dict[str, List[TraceRequest]] = {}
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            d = json.loads(line)
            zone = d.pop("origin")
            out.setdefault(zone, []).append(
                TraceRequest.from_dict(d))
    return out


def fleet_config_for(cfg: GlobeConfig, zone: str,
                     training: bool = False,
                     generation: Optional[str] = None
                     ) -> FleetConfig:
    """The embedded FleetConfig one cell of ``cfg`` runs in ``zone``.
    Module-level (not a GlobeSim method) so shard workers
    (globe/shard.py) build byte-identical cells from the wire copy
    of the config without a parent driver object. ``generation``
    makes this cell's replicas price against that accelerator
    generation (docs/ZOO.md): scheduler-backed cells request the
    generation's accelerator label — the end-to-end
    ``replica_accelerator`` path — analytic cells carry the
    generation name directly."""
    sched_cfg = None
    if cfg.sched:
        kw: Dict[str, object] = {"policy": cfg.sched_policy,
                                 "zone": zone}
        if cfg.cell_pods is not None:
            kw["pods"] = cfg.cell_pods
        if generation is not None:
            from kind_tpu_sim.fleet.costmodel import (
                GENERATION_ACCELERATORS,
                GENERATION_SCHED_TOPOLOGY,
            )

            accel = GENERATION_ACCELERATORS[generation]
            pod_topo, rep_topo = GENERATION_SCHED_TOPOLOGY[accel]
            kw["replica_accelerator"] = accel
            kw["replica_topology"] = rep_topo
            if cfg.cell_pods is None:
                kw["pods"] = ((accel, pod_topo),)
        sched_cfg = FleetSchedConfig(**kw)
    return FleetConfig(
        training=(cfg.training if training else None),
        replicas=cfg.replicas_per_cell, policy=cfg.policy,
        tick_s=cfg.tick_s,
        # the FRONT DOOR is the admission layer: its per-cell
        # hard limit keeps cell queues bounded, so the cell
        # router never sheds on its own (max_queue=0 = no bound)
        max_queue=0,
        max_virtual_s=cfg.max_virtual_s,
        autoscale=cfg.autoscale,
        slo=cfg.slo, sim=cfg.sim,
        autoscaler=cfg.autoscaler,
        zoo=cfg.zoo,
        # a scheduler-backed cell derives its generation from the
        # accelerator label above; an analytic cell carries it
        generations=((generation,)
                     if generation is not None and not cfg.sched
                     else None),
        sched=sched_cfg,
        # cells keep the replica-tier controls (breakers,
        # brownout) but the CLIENT lives at the front door:
        # cell-level retries and hedges stay off
        overload=(dataclasses.replace(cfg.overload,
                                      max_attempts=1,
                                      hedge=False)
                  if cfg.overload is not None else None),
        # cells keep weighted-fair queuing + KV budgets but NOT the
        # quotas — those are charged once, at the front door
        tenancy=(cfg.tenancy.without_quotas()
                 if cfg.tenancy is not None else None),
        fast_forward=False)  # the globe fast-forwards, not cells


# -- the driver --------------------------------------------------------


class GlobeSim:
    """One globe run: cells in name order, one shared clock, the
    front door as the only traffic source, blast-radius chaos at
    planned virtual times."""

    def __init__(self, cfg: GlobeConfig,
                 traces: Optional[Dict[str, List[TraceRequest]]]
                 = None,
                 seed: Optional[int] = None,
                 chaos_events: Sequence[GlobeChaosEvent] = ()):
        self.cfg = cfg
        self.seed = resolve_seed(seed)
        self.clock = VirtualClock()
        self.traces = (traces if traces is not None
                       else generate_globe_traces(cfg, self.seed))
        unknown = set(self.traces) - set(cfg.zones)
        if unknown:
            raise ValueError(
                f"trace zones {sorted(unknown)} not in config "
                f"zones {list(cfg.zones)}")
        for ev in chaos_events:
            if ev.action not in GLOBE_CHAOS_ACTIONS:
                raise ValueError(
                    f"unknown globe chaos action {ev.action!r}; "
                    f"known: {', '.join(GLOBE_CHAOS_ACTIONS)}")
        self.chaos_events = sorted(
            chaos_events, key=lambda e: (e.at_s, e.action, e.target))
        self.chaos_applied: List[dict] = []
        self._zone_idx = {z: i for i, z in enumerate(cfg.zones)}
        self._dcn_factor: Dict[str, float] = {}
        training_cells = set(cfg.resolve_training_cells())
        if training_cells and not cfg.sched:
            raise ValueError(
                "GlobeConfig.training needs scheduler-backed cells "
                "(sched=True): training gangs are scheduler-placed "
                "workloads")
        self.cells = self._build_cells(training_cells)
        self._wire_cells()
        self._cell_by_name = {c.name: c for c in self.cells}
        # overload containment at the client tier (docs/OVERLOAD.md):
        # per-origin retry budgets, per-cell breakers, cross-cell
        # hedging — all timers on EventHeaps, never wall clock
        self.overload = (OverloadState(cfg.overload)
                         if cfg.overload is not None else None)
        # front-door tenancy (docs/TENANCY.md): quotas metered here,
        # once, on fresh arrivals — the cells run without_quotas()
        self.tenancy = (TenancyState(cfg.tenancy)
                        if cfg.tenancy is not None else None)
        self._g_retry = EventHeap()    # (due, ARRIVAL, (req, origin))
        self._g_hedge = EventHeap()    # (due, COMPLETION, ...)
        self._g_attempts: Dict[str, int] = {}
        self._g_hedged: Dict[str, dict] = {}
        self._g_dropped: set = set()
        self._g_completed: set = set()
        self.frontdoor = FrontDoor(cfg.frontdoor, self.cells,
                                   self.rtt_s,
                                   overload=self.overload)
        if self.overload is not None:
            self.frontdoor.on_admit = self._on_admit
        self.planner = (GlobalPlanner(cfg.planner, self.cells)
                        if cfg.planner is not None else None)
        self._next_eval = 0.0
        self.tracker = SloTracker(cfg.slo)
        self._zone_tracker = {z: SloTracker(cfg.slo)
                              for z in cfg.zones}
        self._origin: Dict[str, str] = {}
        self.log: List[dict] = []
        self._arrivals: deque = deque(sorted(
            ((req, zone) for zone, reqs in self.traces.items()
             for req in reqs),
            key=lambda t: (t[0].arrival_s, t[0].request_id)))
        self.requests = len(self._arrivals)
        self._ff = resolve_fast_forward(cfg.fast_forward)
        self._event_core = resolve_event_core(cfg.event_core)
        # empty ticks skipped by fast-forward / boundaries skipped
        # by the event core — observability only, NOT in the report
        # (each mode on/off must diff clean)
        self.ff_skipped = 0
        self.ev_skipped = 0
        # wake-scan backoff (see fleet/sim.py): stepping is always
        # safe, so scan frequency is a pure cost heuristic
        self._scan_holdoff = 0
        self._scan_backoff = 1

    def _build_cells(self, training_cells: set) -> List[Cell]:
        """Cell construction, factored so the sharded driver
        (globe/shard.py) can override it with worker-resident cells
        behind parent-side proxies. With ``generations`` set, cell i
        (name order) runs generation i % len — the mixed-generation
        fleet (docs/ZOO.md)."""
        gens = self.cfg.generations
        return [
            Cell(CellConfig(name=name, zone=name.split("/")[0],
                            fleet=fleet_config_for(
                                self.cfg, name.split("/")[0],
                                training=name in training_cells,
                                generation=(gens[i % len(gens)]
                                            if gens else None))),
                 self.clock)
            for i, name in enumerate(self.cfg.cell_names())]

    def _wire_cells(self) -> None:
        """Hook every cell's completion stream into the globe log /
        trackers — a no-op in the sharded driver, where the hook
        runs on the parent against streamed completion records."""
        for cell in self.cells:
            cell.sim.on_complete = self._completion_hook(cell)

    def _fleet_config(self, zone: str,
                      training: bool = False) -> FleetConfig:
        return fleet_config_for(self.cfg, zone, training=training)

    # -- DCN model ----------------------------------------------------

    def rtt_s(self, z_from: str, z_to: str) -> float:
        """Modeled round trip between a request's origin zone and a
        cell's zone. Inter-zone distance scales with zone-list
        separation; a browned-out link (``dcn_degrade``) inflates
        every path touching the degraded zone by the shared
        tier-parameterized ring cost model (transfer time is inverse
        in the slowest link's bandwidth factor)."""
        zi = self._zone_idx[z_from]
        zj = self._zone_idx[z_to]
        if zi == zj:
            return 2.0 * self.cfg.intra_zone_s
        base = 2.0 * self.cfg.dcn_base_s * (1.0 + 0.5 * abs(zi - zj))
        factor = min(self._dcn_factor.get(z_from, 1.0),
                     self._dcn_factor.get(z_to, 1.0))
        if factor < 1.0:
            base *= collectives.tier_slowdown(factor, 1.0,
                                              tier="dcn")
        return base

    # -- completion stream --------------------------------------------

    def _completion_hook(self, cell: Cell):
        def hook(entry: dict, comp) -> None:
            rid = entry["request_id"]
            now = self.clock.now()
            ov = self.overload
            if ov is not None:
                if rid in self._g_dropped:
                    # cancelled hedge loser finishing anyway: the
                    # winner's stream is the request's one output
                    self._g_dropped.discard(rid)
                    ov.incr("hedge_late_drops")
                    return
                if rid in self._g_completed:
                    return
                pair = self._g_hedged.pop(rid, None)
                if pair is not None:
                    loser_name = (pair["hedge"]
                                  if cell.name == pair["primary"]
                                  else pair["primary"])
                    if cell.name == pair["hedge"]:
                        ov.incr("hedge_wins")
                    loser = self._cell_by_name[loser_name]
                    if loser.cancel(rid):
                        ov.incr("hedge_cancels")
                    else:
                        self._g_dropped.add(rid)
                self._g_completed.add(rid)
            origin = self._origin.get(rid, cell.zone)
            g = dict(entry)
            g["cell"] = cell.name
            g["serving_zone"] = cell.zone
            g["origin"] = origin
            self.log.append(g)
            req = comp.request
            shed = comp.finish_reason == "shed"
            expired = comp.finish_reason == "deadline_exceeded"
            self.tracker.observe(
                arrival_s=req.arrival_s, first_s=comp.first_s,
                finish_s=comp.finish_s, tokens=comp.tokens,
                shed=shed, deadline_exceeded=expired)
            self._zone_tracker[origin].observe(
                arrival_s=req.arrival_s, first_s=comp.first_s,
                finish_s=comp.finish_s, tokens=comp.tokens,
                shed=shed, deadline_exceeded=expired)
            self.frontdoor.note_result(cell.name, g["slo_ok"], now)
            if ov is not None:
                if shed or expired:
                    self._g_maybe_retry(req, origin, now)
                elif comp.first_s is not None:
                    ov.observe_service(comp.finish_s
                                       - comp.dispatch_s,
                                       self._tenant_key(req))
        return hook

    def _tenant_key(self, req) -> str:
        """Per-(origin, tenant) budget key: the declared tenant when
        isolation is on, else "" (the shared per-origin buckets —
        untenanted globes keep their historical streams)."""
        if self.tenancy is not None and self.tenancy.isolation:
            return tenant_of(req)
        return ""

    # -- overload containment at the front door (docs/OVERLOAD.md) ----

    def _on_admit(self, req: TraceRequest, origin: str, cell: Cell,
                  now: float) -> None:
        """Front-door admission hook: arm the cross-cell hedge
        timer at the p9x of observed service times."""
        rid = req.request_id
        if (not self.overload.hedge_enabled()
                or rid in self._g_hedged
                or rid in self._g_completed):
            return
        self._g_hedge.push(now + self.overload.hedge_delay_s(),
                           LANE_COMPLETION, (req, origin, cell.name))

    def _g_fire_hedges(self, now: float) -> None:
        """Due hedge timers: a request still unfinished past its
        hedge delay gets a copy admitted to the second-best cell —
        budget-gated, herd-bounded (candidates already respect the
        hard limit); first completion wins and the loser is
        cancelled wherever it is (even mid-DCN-flight)."""
        ov = self.overload
        for req, origin, primary in self._g_hedge.pop_due(now):
            rid = req.request_id
            if rid in self._g_completed or rid in self._g_hedged:
                continue
            if not ov.hedge_enabled():
                continue
            if not ov.spend_hedge(self._tenant_key(req)):
                continue
            for cand in self.frontdoor._candidates(origin, now):
                if cand.name == primary:
                    continue
                self._g_hedged[rid] = {"primary": primary,
                                       "hedge": cand.name}
                cand.admit(req, now + self.rtt_s(origin, cand.zone))
                ov.incr("hedges_issued")
                ov.breaker_dispatch(cand.name)
                break

    def _g_maybe_retry(self, req: TraceRequest, origin: str,
                       now: float) -> None:
        """The per-origin client retry model: a shed or expired
        attempt retries after deterministic doubling backoff IF the
        origin zone's token-bucket budget allows — the suppressed
        count is the proof that a saturated globe sees retry load
        shrink, not amplify."""
        ov = self.overload
        if ov.cfg.max_attempts <= 1:
            return
        base = req.request_id.split("~r", 1)[0]
        attempt = self._g_attempts.get(base, 1)
        if attempt >= ov.cfg.max_attempts:
            ov.incr("retries_exhausted")
            return
        if not ov.spend_retry(origin, self._tenant_key(req)):
            return
        self._g_attempts[base] = attempt + 1
        delay = ov.cfg.retry_backoff_s * (2 ** (attempt - 1))
        at = round(now + delay, 6)
        retry = dataclasses.replace(
            req, request_id=f"{base}~r{attempt}", arrival_s=at)
        self._origin[retry.request_id] = origin
        self._g_retry.push(at, LANE_ARRIVAL, (retry, origin))

    def _record_frontdoor_shed(self, req: TraceRequest,
                               origin: str, now: float,
                               retryable: bool = True) -> None:
        entry = {
            "request_id": req.request_id,
            "cell": None, "serving_zone": None, "origin": origin,
            "replica": -1, "prefix_group": req.prefix_group,
            "arrival_s": round(req.arrival_s, 6),
            "dispatch_s": round(now, 6), "first_s": None,
            "finish_s": round(now, 6), "tokens": 0,
            "tokens_crc": 0, "finish_reason": "shed",
            "slo_ok": False,
        }
        if getattr(req, "tenant", ""):
            entry["tenant"] = req.tenant
        if getattr(req, "model", ""):
            entry["model"] = req.model
        self.log.append(entry)
        self.tracker.observe(
            arrival_s=req.arrival_s, first_s=None, finish_s=now,
            tokens=0, shed=True)
        self._zone_tracker[origin].observe(
            arrival_s=req.arrival_s, first_s=None, finish_s=now,
            tokens=0, shed=True)
        if self.overload is not None:
            self._g_completed.add(req.request_id)
            if retryable:
                self._g_maybe_retry(req, origin, now)

    # -- blast-radius chaos -------------------------------------------

    def _cells_of(self, zone: str) -> List[Cell]:
        return [c for c in self.cells if c.zone == zone]

    def _apply_chaos(self, now: float) -> None:
        while self.chaos_events and self.chaos_events[0].at_s <= now:
            ev = self.chaos_events.pop(0)
            self.chaos_applied.append(
                dict(ev.as_dict(), applied_at_s=round(now, 6)))
            if ev.action in ("zone_loss", "herd_failover"):
                self._lose_zone(ev.target, now, ev.action)
            elif ev.action == "zone_restore":
                for cell in self._cells_of(ev.target):
                    cell.restore(now)
                metrics.globe_board().incr("zone_restores")
                metrics.recovery_log().record(
                    "globe_zone_restore", zone=ev.target,
                    at_s=round(now, 6))
            elif ev.action == "dcn_degrade":
                self._dcn_factor[ev.target] = max(1e-3, ev.param)
                metrics.globe_board().incr("dcn_degrades")
                metrics.recovery_log().record(
                    "globe_dcn_degrade", zone=ev.target,
                    factor=ev.param, at_s=round(now, 6))
            elif ev.action == "dcn_restore":
                self._dcn_factor.pop(ev.target, None)
                metrics.globe_board().incr("dcn_restores")
                metrics.recovery_log().record(
                    "globe_dcn_restore", zone=ev.target,
                    at_s=round(now, 6))
            elif ev.action == "cell_drain":
                for cell in self.cells:
                    if cell.name == ev.target:
                        cell.draining = True
                metrics.globe_board().incr("cell_drains")
                metrics.recovery_log().record(
                    "globe_cell_drain", cell=ev.target,
                    at_s=round(now, 6))
            elif ev.action == "cell_undrain":
                for cell in self.cells:
                    if cell.name == ev.target:
                        cell.draining = False
                metrics.globe_board().incr("cell_undrains")

    def _lose_zone(self, zone: str, now: float,
                   action: str) -> None:
        """A whole zone goes dark: every cell in it fails, and its
        entire displaced load re-enters the front door in arrival
        order — the thundering herd the spill bound must absorb
        without cascading into the survivors."""
        displaced: List[TraceRequest] = []
        for cell in self._cells_of(zone):
            displaced.extend(cell.fail(now))
        displaced.sort(key=lambda r: (r.arrival_s, r.request_id))
        metrics.globe_board().incr("zone_losses")
        metrics.recovery_log().record(
            f"globe_{action}", zone=zone,
            displaced=len(displaced), at_s=round(now, 6))
        for req in displaced:
            origin = self._origin.get(req.request_id, zone)
            shed = self.frontdoor.offer(req, origin, now,
                                        readmit=True)
            if shed is not None:
                self._record_frontdoor_shed(req, origin, now)

    # -- the loop -----------------------------------------------------

    def _done(self) -> bool:
        return bool(
            not self._arrivals and not self.frontdoor.queue
            and not self.chaos_events
            and not self._g_retry and not self._g_hedge
            and all(c.quiescent() for c in self.cells))

    def _skip_uninteresting(self, tick: float) -> None:
        """The event-core jump at globe scale (docs/PERFORMANCE.md
        "The event core"): cells stop being per-tick steppers and
        become event producers — each answers when anything inside
        it (DCN delivery, slot event, warm-up, scheduler activity)
        next lands, the front door and planner contribute their own
        instants, and every boundary in between is skipped by the
        identical tick-sized float additions. Skipped boundaries
        still count into each ALIVE cell's tick-grid index so
        per-cell autoscaler cadences land on the identical
        boundaries as the lockstep loop (a dead cell's index is
        frozen either way — it is not stepped)."""
        # dense-path fast exits: this boundary will be stepped no
        # matter what — skip the cell scan
        b = self.clock.now()
        if self._arrivals and self._arrivals[0][0].arrival_s <= b:
            return
        if self._scan_holdoff > 0:
            self._scan_holdoff -= 1
            return
        if self.chaos_events and self.chaos_events[0].at_s <= b:
            return
        if self.frontdoor.queue:
            return
        due = DueSet()
        if self._arrivals:
            due.at(self._arrivals[0][0].arrival_s)
        if self.chaos_events:
            due.at(self.chaos_events[0].at_s)
        if self.planner is not None:
            due.at(self._next_eval)
        # front-door retry/hedge timers are boundary-condition
        # events like arrivals
        due.at(self._g_retry.peek_time())
        due.at(self._g_hedge.peek_time())
        if self.frontdoor.queue:
            due.need_now()
        alive_sims = []
        evals_away = -1
        for cell in self.cells:
            due.merge(cell.event_due())
            if cell.alive:
                sim = cell.sim
                alive_sims.append(sim)
                if (sim.autoscaler is not None
                        or sim.overload is not None
                        or (sim.trainer is not None
                            and sim.trainer.wants_evals())):
                    # cell brownout ladders and training elastic
                    # ladders evaluate on the same tick grid as
                    # autoscalers — eval boundaries must be
                    # stepped in both modes
                    r = sim._ticks % sim._eval_ticks
                    away = (sim._eval_ticks - r) % sim._eval_ticks
                    if evals_away < 0 or away < evals_away:
                        evals_away = away
        if due.immediate or evals_away == 0:
            return
        due_ge = due.ge
        due_cover = due.cover
        limit = self.cfg.max_virtual_s
        adv = self.clock.advance
        now = self.clock.now
        skipped = 0
        while True:
            b = now()
            if b > limit or due_ge <= b or due_cover <= b + tick:
                break
            adv(tick)
            for sim in alive_sims:
                sim._ticks += 1
            skipped += 1
            if evals_away > 0:
                evals_away -= 1
                if evals_away == 0:
                    break
        self.ev_skipped += skipped
        if skipped:
            self._scan_backoff = 1
        else:
            self._scan_holdoff = self._scan_backoff
            self._scan_backoff = min(self._scan_backoff * 2, 32)

    def _advance(self, tick: float) -> None:
        """One clock tick — then, with the event core enabled, past
        every provably uninteresting boundary; or, across a globally
        idle gap (every cell idle, front door drained, no planner)
        with the legacy fast-forward, every empty tick up to the
        next arrival/chaos event. Always by the same sequence of
        tick-sized additions (byte-identical replays, docs/FLEET.md
        fast-forward contract)."""
        self.clock.advance(tick)
        if self._event_core:
            self._skip_uninteresting(tick)
            return
        if (not self._ff or self.planner is not None
                or self.overload is not None):
            return
        if self.frontdoor.queue:
            return
        if not all(c.idle_gap() for c in self.cells):
            return
        next_s = (self._arrivals[0][0].arrival_s
                  if self._arrivals else float("inf"))
        if self.chaos_events:
            next_s = min(next_s, self.chaos_events[0].at_s)
        limit = self.cfg.max_virtual_s
        adv = self.clock.advance
        now = self.clock.now
        while now() < next_s and now() <= limit:
            adv(tick)
            self.ff_skipped += 1

    def run(self) -> Dict[str, object]:
        board_before = metrics.globe_board().counts()
        self._tenant_before = metrics.tenant_board().counts()
        self._zoo_before = metrics.zoo_board().counts()
        tick = resolve_tick_s(self.cfg.tick_s)
        # origin map first: displaced requests keep their origin
        # wherever they complete
        for zone, reqs in self.traces.items():
            for req in reqs:
                self._origin[req.request_id] = zone
        while True:
            now = self.clock.now()
            if now > self.cfg.max_virtual_s:
                break
            self._apply_chaos(now)
            if self.planner is not None:
                while now >= self._next_eval:
                    self.planner.evaluate(now)
                    self._next_eval = round(
                        self._next_eval
                        + self.cfg.planner.eval_every_s, 9)
            while (self._arrivals
                   and self._arrivals[0][0].arrival_s <= now):
                req, origin = self._arrivals.popleft()
                if self.tenancy is not None:
                    # quota check FIRST: a quota-refused request
                    # never funds a retry budget nor retries itself
                    if self.tenancy.admit(req, now) is not None:
                        metrics.tenant_board().incr(
                            "tenant_quota_shed")
                        self._record_frontdoor_shed(
                            req, origin, now, retryable=False)
                        continue
                if self.overload is not None:
                    # first-attempt admissions fund the origin's
                    # retry budget
                    self.overload.earn_retry(
                        origin, self._tenant_key(req))
                shed = self.frontdoor.offer(req, origin, now)
                if shed is not None:
                    self._record_frontdoor_shed(req, origin, now)
            if self.overload is not None:
                for req, origin in self._g_retry.pop_due(now):
                    shed = self.frontdoor.offer(req, origin, now)
                    if shed is not None:
                        self._record_frontdoor_shed(req, origin,
                                                    now)
            self.frontdoor.pump(now)
            if self.overload is not None:
                self._g_fire_hedges(now)
            for cell in self.cells:
                cell.deliver_due(now)
                cell.step(now, tick)
            if self._done():
                break
            self._advance(tick)
        self.log.sort(key=lambda e: (e["finish_s"],
                                     e["request_id"]))
        return self._report(board_before)

    # -- reporting ----------------------------------------------------

    def _report(self, board_before: Dict[str, int]
                ) -> Dict[str, object]:
        span = self.clock.now()
        served_local = sum(
            1 for e in self.log
            if e["serving_zone"] is not None
            and e["serving_zone"] == e["origin"])
        zones: Dict[str, dict] = {}
        for zone in self.cfg.zones:
            entries = [e for e in self.log
                       if e["origin"] == zone]
            zones[zone] = {
                "requests": len(entries),
                "spilled_out": sum(
                    1 for e in entries
                    if e["serving_zone"] is not None
                    and e["serving_zone"] != zone),
                "shed": sum(1 for e in entries
                            if e["finish_reason"] == "shed"),
                "slo": self._zone_tracker[zone].report(
                    span_s=span),
            }
        report: Dict[str, object] = {
            "config": self.cfg.as_dict(),
            "seed": self.seed,
            "requests": self.requests,
            "completed": len(self.log),
            "virtual_s": round(span, 6),
            "global_slo": self.tracker.report(span_s=span),
            "served_in_origin_zone": served_local,
            "zones": zones,
            "cells": {c.name: c.report() for c in self.cells},
            "frontdoor": self.frontdoor.report(),
            "completions": self.log,
            "globe_counters":
                metrics.globe_board().snapshot_since(board_before),
            "ok": len(self.log) == self.requests,
        }
        if self.overload is not None:
            # with retries the log carries one entry per ATTEMPT;
            # ok when every original request reached a terminal
            # outcome (its base id appears)
            base_done = {e["request_id"].split("~r", 1)[0]
                         for e in self.log}
            report["ok"] = all(
                req.request_id in base_done
                for reqs in self.traces.values() for req in reqs)
            report["overload"] = self.overload.report()
        if self.tenancy is not None:
            ten_report = self.tenancy.report()
            ten_report["counters"] = metrics.tenant_board(
                ).snapshot_since(self._tenant_before)
            report["tenancy"] = ten_report
        trainers = {c.name: c.sim.trainer for c in self.cells
                    if c.sim.trainer is not None}
        if trainers:
            # the globe-level training roll-up: per-cell detail
            # lives in cells[*].training; the verdict joins ok
            trep = {name: t.report()
                    for name, t in sorted(trainers.items())}
            report["training"] = {
                "cells": sorted(trainers),
                "all_done": all(t["all_done"]
                                for t in trep.values()),
                "ledger_ok": all(t["ledger_ok"]
                                 for t in trep.values()),
                "lost_steps": sum(t["lost_steps"]
                                  for t in trep.values()),
                "rerun_steps": sum(t["rerun_steps"]
                                   for t in trep.values()),
            }
            report["ok"] = bool(report["ok"]
                                and report["training"]["ledger_ok"])
        if self.cfg.zoo is not None:
            report["zoo"] = {
                "warm": {c.name: sorted(c.models_warm())
                         for c in self.cells},
                "counters": metrics.zoo_board().snapshot_since(
                    self._zoo_before),
            }
        if self.chaos_applied:
            report["chaos"] = self.chaos_applied
        if self.planner is not None:
            report["planner"] = self.planner.report()
        return report


def attainment_over(log: Sequence[dict], t_from: float,
                    t_to: float = float("inf"),
                    zone: Optional[str] = None) -> Optional[float]:
    """SLO attainment over requests ARRIVING in a window, optionally
    restricted to one origin zone — how the globe chaos scenarios
    judge recovery and containment without the backlog-drain period
    polluting the number."""
    window = [e for e in log
              if t_from <= e["arrival_s"] < t_to
              and (zone is None or e["origin"] == zone)]
    if not window:
        return None
    return sum(1 for e in window if e["slo_ok"]) / len(window)
