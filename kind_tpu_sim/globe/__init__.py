"""Multi-cell / multi-zone fleet-of-fleets simulator (docs/GLOBE.md).

The layer above the fleet: zones group cells (each cell = one sched
inventory + one FleetSim) into correlated failure domains behind a
global anycast-style front door (latency/capacity-aware admission,
bounded cross-cell spill, sticky prefix-affinity), with a global
capacity planner trading a spot-replica budget across zones above
the per-cell autoscalers, and blast-radius chaos — zone loss, DCN
brown-out, thundering-herd failover, cell drain. Same seed, same
config => byte-identical reports.

Knobs: KIND_TPU_SIM_GLOBE_SEED (sim.resolve_seed), plus every fleet/
sched/health knob the embedded cells inherit.
"""

from kind_tpu_sim.globe.cell import (  # noqa: F401
    Cell,
    CellConfig,
)
from kind_tpu_sim.globe.frontdoor import (  # noqa: F401
    FrontDoor,
    FrontDoorConfig,
)
from kind_tpu_sim.fleet.overload import (  # noqa: F401
    OverloadConfig,
    OverloadState,
)
from kind_tpu_sim.globe.planner import (  # noqa: F401
    GlobalPlanner,
    PlannerConfig,
)
from kind_tpu_sim.globe.sim import (  # noqa: F401
    GLOBE_CHAOS_ACTIONS,
    GLOBE_SEED_ENV,
    GlobeChaosEvent,
    GlobeConfig,
    GlobeSim,
    GlobeWorkloadSpec,
    attainment_over,
    generate_globe_traces,
    load_globe_trace,
    resolve_seed,
    save_globe_trace,
    zone_seed,
)
from kind_tpu_sim.globe.shard import (  # noqa: F401
    CellProxy,
    ShardedGlobeSim,
    resolve_shards,
)
