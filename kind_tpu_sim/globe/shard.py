"""Sharded GlobeSim: cells partitioned across worker processes.

:class:`ShardedGlobeSim` runs the same globe as
:class:`~kind_tpu_sim.globe.sim.GlobeSim`, with the cells split
round-robin across a pool of worker processes (the zero-copy
worker protocol in utils/worker_pool.py) and driven through
parent-side :class:`CellProxy` stand-ins. Reports are
**byte-identical** to the single-process driver — sharding is an
execution strategy like fast-forward and the event core, chosen at
the driver (``ShardedGlobeSim`` / ``globe run --shards``), never in
``GlobeConfig``, so it cannot drift into ``as_dict()``.

How byte-identity holds, by construction:

* **The parent replicates the lockstep loop's boundary decisions
  exactly.** Every input to ``_skip_uninteresting`` lives on the
  parent or in state that cannot change while a shard is idle:
  arrivals, chaos, front-door queue, each shard's merged
  :class:`~kind_tpu_sim.fleet.events.DueSet` (refreshed post-step,
  and cell event horizons only move when a cell is stepped or takes
  an op — both of which refresh the cache), and a parent-side
  mirror of every cell's tick-grid index for autoscaler cadence
  (``B - tick_debt``: boundaries completed, minus boundaries missed
  while dead).
* **A stepped boundary dispatches one window job per shard that
  needs it** (pending ops, due work, or an eval-due cell); the
  others are provably no-ops — the event core's partition
  invariance argument applied per shard. Each worker advances its
  clock by the owed tick count (the identical tick-sized float
  additions, so worker and parent clocks agree bit-for-bit),
  applies queued ops in parent call order, then delivers and steps
  its cells in name order.
* **Completion merge order matches lockstep.** A completion with
  finish time t is observed at the unique grid boundary b with
  b < t <= b + tick in EVERY mode (``cell.step(b, tick)`` processes
  ``(b, b+tick]`` and the cover bound forces b to be stepped), so
  concatenating the stepped shards' completion buffers and stable
  sorting by global cell index reproduces the lockstep sequence:
  per boundary, cells in name order, hook-call order within a cell.
  The parent then applies the unchanged ``_completion_hook`` to
  each record (log, SLO trackers, front-door feedback all live on
  the parent).
* **Chaos is a synchronization point.** ``cell.fail`` needs its
  displaced load immediately (the herd re-enters the front door at
  the same boundary), so a proxy ``fail`` flushes the shard's
  pending ops plus the fail in one synchronous job; restore /
  drain / warm / admit ride the ordered per-shard op queue into the
  next window. Alive status therefore only changes at boundaries
  both sides observe.

Worker crashes are survivable and invisible in the report: every
job is journaled per shard, and a crashed worker is respawned (with
any injected ``CHAOS_FAULT`` env stripped, so a crash fault cannot
re-fire during recovery) and replayed from genesis — determinism
makes the replayed answer THE answer.

Scaling honesty (docs/PERFORMANCE.md): each stepped boundary costs
one IPC round trip per dispatched shard, so sharding pays off when
per-boundary cell work dominates that round trip — many cells, or
heavy (scheduler-backed, large-replica) cells. For small globes the
single-process driver is faster; the columnar fleet state is where
the headline per-event cost win lives.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional, Sequence, Tuple

from kind_tpu_sim import metrics
from kind_tpu_sim.analysis import knobs
from kind_tpu_sim.fleet.autoscaler import AutoscalerConfig
from kind_tpu_sim.fleet.events import DueSet
from kind_tpu_sim.fleet.loadgen import TraceRequest, VirtualClock
from kind_tpu_sim.fleet.router import SimReplicaConfig
from kind_tpu_sim.fleet.sim import resolve_tick_s
from kind_tpu_sim.fleet.slo import SloPolicy
from kind_tpu_sim.globe.cell import Cell, CellConfig
from kind_tpu_sim.globe.sim import (
    GlobeConfig,
    GlobeSim,
    fleet_config_for,
)
from kind_tpu_sim.utils import worker_pool as wp

_INF = float("inf")

# worker job targets, resolved by the pool's generic "call" job
_INIT = "kind_tpu_sim.globe.shard:job_shard_init"
_WINDOW = "kind_tpu_sim.globe.shard:job_shard_window"
_REPORT = "kind_tpu_sim.globe.shard:job_shard_report"


def resolve_shards(value: Optional[int] = None) -> int:
    """Explicit value > env (KIND_TPU_SIM_GLOBE_SHARDS) > 0 (off)."""
    if value is not None:
        return int(value)
    return int(knobs.get(knobs.GLOBE_SHARDS))


# -- the wire copy of the config ---------------------------------------
#
# Only the fields cell construction consumes (fleet_config_for +
# cell naming); planner / overload / training / tenancy are rejected
# up front (v1) and the front door never leaves the parent.


def config_to_wire(cfg: GlobeConfig) -> dict:
    return {
        "zones": list(cfg.zones),
        "cells_per_zone": cfg.cells_per_zone,
        "replicas_per_cell": cfg.replicas_per_cell,
        "policy": cfg.policy,
        "tick_s": cfg.tick_s,
        "max_virtual_s": cfg.max_virtual_s,
        "sim": dataclasses.asdict(cfg.sim),
        "slo": dataclasses.asdict(cfg.slo),
        "sched": cfg.sched,
        "sched_policy": cfg.sched_policy,
        "cell_pods": ([list(p) for p in cfg.cell_pods]
                      if cfg.cell_pods is not None else None),
        "autoscale": cfg.autoscale,
        "autoscaler": dataclasses.asdict(cfg.autoscaler),
    }


def config_from_wire(d: dict) -> GlobeConfig:
    return GlobeConfig(
        zones=tuple(d["zones"]),
        cells_per_zone=d["cells_per_zone"],
        replicas_per_cell=d["replicas_per_cell"],
        policy=d["policy"],
        tick_s=d["tick_s"],
        max_virtual_s=d["max_virtual_s"],
        sim=SimReplicaConfig(**d["sim"]),
        slo=SloPolicy(**d["slo"]),
        sched=d["sched"],
        sched_policy=d["sched_policy"],
        cell_pods=(tuple(tuple(p) for p in d["cell_pods"])
                   if d["cell_pods"] is not None else None),
        autoscale=d["autoscale"],
        autoscaler=AutoscalerConfig(**d["autoscaler"]))


# -- worker side -------------------------------------------------------
#
# One session per worker process, holding this shard's cells on a
# private VirtualClock kept bit-identical to the parent's (the same
# chain of tick-sized additions from 0.0). The tick-grid contract:
# every cell's ``_ticks`` must count every completed boundary of its
# alive spans, exactly once — ``step`` counts a stepped landing
# boundary, the advance loop counts interior boundaries, and the
# ``uncounted`` flag settles a landing boundary this shard was never
# stepped at (chaos-only jobs, or a skipped boundary-0) when the
# next job advances away from it.

_SESSION: Optional[dict] = None


def _buffer_hook(buf: List[dict], ci: int):
    def hook(entry: dict, comp) -> None:
        buf.append({
            "ci": ci,
            "entry": entry,
            "req": comp.request.as_dict(),
            "first_s": comp.first_s,
            "finish_s": comp.finish_s,
            "tokens": comp.tokens,
            "finish_reason": comp.finish_reason,
        })
    return hook


def _count_tick(cells: Sequence[Cell]) -> None:
    for cell in cells:
        if cell.alive:
            cell.sim._ticks += 1


def _snapshots(s: dict) -> List[list]:
    return [[ci, {"out": cell.outstanding(),
                  "routable": cell.routable_replicas(),
                  "quiescent": cell.quiescent()}]
            for ci, cell in zip(s["cis"], s["cells"])]


def _merged_due(s: dict) -> dict:
    due = DueSet()
    for cell in s["cells"]:
        due.merge(cell.event_due())
    return {"immediate": due.immediate,
            "ge": None if due.ge == _INF else due.ge,
            "cover": None if due.cover == _INF else due.cover}


def job_shard_init(cfg: dict, names: Sequence[str],
                   indices: Sequence[int], tick: float) -> dict:
    global _SESSION
    gcfg = config_from_wire(cfg)
    clock = VirtualClock()
    cells = [
        Cell(CellConfig(name=name, zone=name.split("/")[0],
                        fleet=fleet_config_for(
                            gcfg, name.split("/")[0])),
             clock)
        for name in names]
    buf: List[dict] = []
    cis = list(indices)
    for ci, cell in zip(cis, cells):
        cell.sim.on_complete = _buffer_hook(buf, ci)
    _SESSION = {
        "clock": clock, "tick": float(tick),
        "cells": cells, "cis": cis,
        "by_ci": dict(zip(cis, cells)),
        "buf": buf,
        # boundary 0 is the current landing and has not been
        # stepped here yet — see the tick-grid contract above
        "uncounted": True,
    }
    return {"eval_ticks": cells[0].sim._eval_ticks,
            "cells": _snapshots(_SESSION),
            "due": _merged_due(_SESSION)}


def job_shard_window(advance: int = 0, ops: Sequence[list] = (),
                     step: bool = True) -> dict:
    s = _SESSION
    assert s is not None, "job_shard_init must run first"
    clock, tick, cells = s["clock"], s["tick"], s["cells"]
    if advance and s["uncounted"]:
        # the boundary we are leaving was never stepped here;
        # count it now (alive status is unchanged since then —
        # it only moves via ops, and none arrived in between)
        _count_tick(cells)
        s["uncounted"] = False
    for i in range(advance):
        clock.advance(tick)
        if i < advance - 1:
            _count_tick(cells)
    now = clock.now()
    by_ci = s["by_ci"]
    displaced: List[list] = []
    for op in ops:
        kind, ci = op[0], op[1]
        cell = by_ci[ci]
        if kind == "admit":
            cell.admit(TraceRequest.from_dict(op[2]), op[3])
        elif kind == "warm":
            cell.warm_prefix(op[2])
        elif kind == "drain":
            cell.draining = bool(op[2])
        elif kind == "restore":
            cell.restore(op[2])
        elif kind == "fail":
            displaced.append(
                [ci, [r.as_dict() for r in cell.fail(op[2])]])
        else:
            raise ValueError(f"unknown shard op {kind!r}")
    if step:
        for cell in cells:
            cell.deliver_due(now)
            cell.step(now, tick)
        s["uncounted"] = False
    else:
        s["uncounted"] = True
    buf = s["buf"]
    completions = list(buf)
    buf.clear()
    resp = {"completions": completions,
            "cells": _snapshots(s),
            "due": _merged_due(s)}
    if displaced:
        resp["displaced"] = displaced
    return resp


def job_shard_report() -> List[list]:
    s = _SESSION
    assert s is not None, "job_shard_init must run first"
    return [[ci, cell.report()]
            for ci, cell in zip(s["cis"], s["cells"])]


# -- parent side -------------------------------------------------------


class _Comp:
    """The completion view ``_completion_hook`` reads, rebuilt from
    a streamed record."""

    __slots__ = ("request", "first_s", "finish_s", "tokens",
                 "finish_reason", "dispatch_s")

    def __init__(self, rec: dict):
        self.request = TraceRequest.from_dict(rec["req"])
        self.first_s = rec["first_s"]
        self.finish_s = rec["finish_s"]
        self.tokens = rec["tokens"]
        self.finish_reason = rec["finish_reason"]
        self.dispatch_s = None  # only read under overload (not in v1)


class _SimShim:
    """What ``_report`` peeks at through ``cell.sim`` — training is
    rejected up front in v1, so the trainer is always absent."""

    trainer = None


class _ShardHandle:
    """One worker process: its cells, op queue, owed clock advances,
    cached due horizon, and the replayable job journal."""

    __slots__ = ("index", "proc", "env", "cis",
                 "pending", "owed", "due", "journal",
                 "crashed", "sent")

    def __init__(self, index: int, env: Dict[str, str],
                 cis: List[int]):
        self.index = index
        self.env = env
        self.cis = cis
        self.proc = wp.PoolWorker(env)
        self.pending: List[list] = []
        self.owed = 0
        self.due: Tuple[bool, float, float] = (True, _INF, _INF)
        self.journal: List[Tuple[str, dict]] = []
        self.crashed = False
        self.sent = 0


class CellProxy:
    """Parent-side stand-in for a worker-resident cell: exactly the
    surface the front door, chaos, and the run loop touch. Counters
    are exact, not approximate — between a shard's stepped
    boundaries its cells only change through ops the proxy itself
    queued, so last-snapshot + queued-admits reproduces the worker
    value at every parent read."""

    __slots__ = ("_driver", "ci", "name", "zone", "_slots", "shard",
                 "sim", "alive", "_draining", "peak_outstanding",
                 "_out", "_admits", "_routable", "_quiescent",
                 "_routable_at_fail", "tick_debt", "_died_at",
                 "_report")

    def __init__(self, driver: "ShardedGlobeSim", ci: int,
                 name: str, slots: int):
        self._driver = driver
        self.ci = ci
        self.name = name
        self.zone = name.split("/")[0]
        self._slots = slots
        self.shard: Optional[_ShardHandle] = None
        self.sim = _SimShim()
        self.alive = True
        self._draining = False
        self.peak_outstanding = 0
        self._out = 0
        self._admits = 0
        self._routable = 0
        self._quiescent = True
        self._routable_at_fail = 0
        self.tick_debt = 0
        self._died_at = 0
        self._report: Optional[dict] = None

    # -- the front-door surface ---------------------------------------

    def outstanding(self) -> int:
        return self._out + self._admits

    def capacity(self) -> int:
        return self._routable * self._slots

    def routable_replicas(self) -> int:
        return self._routable

    def routable(self) -> bool:
        return (self.alive and not self._draining
                and self._routable > 0)

    def admit(self, req: TraceRequest, deliver_s: float) -> None:
        self._driver._enqueue(
            self, ["admit", self.ci, req.as_dict(), deliver_s])
        self._admits += 1
        out = self.outstanding()
        if out > self.peak_outstanding:
            # matches the worker cell exactly: both sides see the
            # identical admit/completion sequence at each boundary
            self.peak_outstanding = out

    def warm_prefix(self, group: int) -> None:
        self._driver._enqueue(self, ["warm", self.ci, group])

    # -- the chaos / loop surface -------------------------------------

    @property
    def draining(self) -> bool:
        return self._draining

    @draining.setter
    def draining(self, flag: bool) -> None:
        flag = bool(flag)
        self._draining = flag
        self._driver._enqueue(self, ["drain", self.ci, flag])

    def quiescent(self) -> bool:
        return self._quiescent and self._admits == 0

    def fail(self, now: float) -> List[TraceRequest]:
        return self._driver._fail_cell(self, now)

    def restore(self, now: float) -> None:
        self._driver._restore_cell(self, now)

    def report(self) -> dict:
        assert self._report is not None, "report fetched post-run"
        return self._report


class ShardedGlobeSim(GlobeSim):
    """GlobeSim with worker-resident cells. Same constructor plus
    ``shards`` (None reads KIND_TPU_SIM_GLOBE_SHARDS); reports are
    byte-identical to the single-process driver — replaycheck's
    referee holds across shard counts and seeds."""

    def __init__(self, cfg: GlobeConfig, traces=None, seed=None,
                 chaos_events: Sequence = (),
                 shards: Optional[int] = None,
                 rpc_timeout_s: float = 600.0,
                 _test_kill: Optional[Tuple[int, int]] = None):
        for field, label in ((cfg.overload, "overload"),
                             (cfg.planner, "planner"),
                             (cfg.training, "training"),
                             (cfg.tenancy, "tenancy"),
                             (cfg.zoo, "zoo"),
                             (cfg.generations, "generations")):
            if field is not None:
                raise ValueError(
                    f"sharded GlobeSim does not support "
                    f"GlobeConfig.{label} yet — run the "
                    f"single-process driver")
        self._n_shards = max(1, resolve_shards(shards))
        self._rpc_timeout_s = rpc_timeout_s
        # test hook: (shard index, nth job sent to it) — the parent
        # kills the worker after sending that job, exercising the
        # journal respawn+replay path mid-window
        self._test_kill = _test_kill
        self._seq = 0
        self._boundaries = 0  # completed grid boundaries (B)
        self._shards: List[_ShardHandle] = []
        self._proxies: List[CellProxy] = []
        self._eval_ticks = 1
        self._hooks: List = []
        self._closed = False
        super().__init__(cfg, traces=traces, seed=seed,
                         chaos_events=chaos_events)

    # -- construction --------------------------------------------------

    def _build_cells(self, training_cells: set) -> List[CellProxy]:
        names = self.cfg.cell_names()
        n = max(1, min(self._n_shards, len(names)))
        self._n_shards = n
        tick = resolve_tick_s(self.cfg.tick_s)
        slots = getattr(self.cfg.sim, "max_slots", 1)
        self._proxies = [CellProxy(self, ci, name, slots)
                         for ci, name in enumerate(names)]
        wire = config_to_wire(self.cfg)
        env = wp.pool_child_env(warm=False)
        self._shards = [
            _ShardHandle(s, env, list(range(s, len(names), n)))
            for s in range(n)]
        for sh in self._shards:
            for ci in sh.cis:
                self._proxies[ci].shard = sh
            self._send(sh, _INIT,
                       {"cfg": wire,
                        "names": [names[ci] for ci in sh.cis],
                        "indices": sh.cis, "tick": tick})
        for sh in self._shards:
            result = self._collect(sh)
            self._eval_ticks = result["eval_ticks"]
            self._absorb(sh, result)
        return self._proxies

    def _wire_cells(self) -> None:
        # completions stream back as records; the unchanged hook
        # runs on the parent against each one
        self._hooks = [self._completion_hook(c) for c in self.cells]

    # -- the journaled RPC layer --------------------------------------

    def _request(self, sh: _ShardHandle, target: str,
                 kwargs: dict) -> dict:
        self._seq += 1
        req = {"id": self._seq, "job": "call",
               "kwargs": {"target": target, "kwargs": kwargs}}
        deadline = (time.monotonic()  # detlint: ok(wallclock) -- subprocess IO deadline, never feeds the report
                    + self._rpc_timeout_s)
        sh.proc.ensure_ready(deadline)
        sh.proc.send(req)
        resp = sh.proc.read_frame(deadline)
        if not resp.get("ok"):
            raise RuntimeError(
                f"globe shard {sh.index} job failed: "
                f"{resp.get('error')}\n{resp.get('traceback', '')}")
        return resp["result"]

    def _send(self, sh: _ShardHandle, target: str,
              kwargs: dict) -> None:
        """Journal and dispatch one job; a dead pipe is noted, not
        raised — ``_collect`` runs the recovery."""
        sh.journal.append((target, kwargs))
        sh.sent += 1
        self._seq += 1
        req = {"id": self._seq, "job": "call",
               "kwargs": {"target": target, "kwargs": kwargs}}
        deadline = (time.monotonic()  # detlint: ok(wallclock) -- subprocess IO deadline, never feeds the report
                    + self._rpc_timeout_s)
        try:
            sh.proc.ensure_ready(deadline)
            sh.proc.send(req)
            if (self._test_kill is not None
                    and self._test_kill == (sh.index, sh.sent)):
                self._test_kill = None
                sh.proc.kill()
        except wp.WorkerCrash:
            sh.crashed = True

    def _collect(self, sh: _ShardHandle) -> dict:
        if sh.crashed:
            sh.crashed = False
            return self._respawn_replay(sh)
        deadline = (time.monotonic()  # detlint: ok(wallclock) -- subprocess IO deadline, never feeds the report
                    + self._rpc_timeout_s)
        try:
            resp = sh.proc.read_frame(deadline)
        except (wp.WorkerCrash, TimeoutError):
            return self._respawn_replay(sh)
        if not resp.get("ok"):
            raise RuntimeError(
                f"globe shard {sh.index} job failed: "
                f"{resp.get('error')}\n{resp.get('traceback', '')}")
        return resp["result"]

    def _respawn_replay(self, sh: _ShardHandle) -> dict:
        """Fresh process, journal replayed from genesis; the final
        replayed job is the one that crashed, and determinism makes
        its replayed answer THE answer."""
        try:
            sh.proc.kill()
        except Exception:
            pass
        env = dict(sh.env)
        # an env-injected crash fault would re-fire at the same job
        # number forever; a respawn heals (run_grid does the same)
        env.pop(wp.CHAOS_FAULT_ENV, None)
        sh.proc = wp.PoolWorker(env)
        metrics.recovery_log().record(
            "globe_shard_respawn", shard=sh.index,
            jobs=len(sh.journal))
        last: Optional[dict] = None
        for target, kwargs in sh.journal:
            last = self._request(sh, target, kwargs)
        assert last is not None
        return last

    # -- proxy callbacks ----------------------------------------------

    def _enqueue(self, proxy: CellProxy, op: list) -> None:
        proxy.shard.pending.append(op)

    def _fail_cell(self, proxy: CellProxy,
                   now: float) -> List[TraceRequest]:
        """Synchronous: the displaced load re-enters the front door
        at this same boundary, so the shard settles its queued ops
        plus the fail before the parent proceeds."""
        sh = proxy.shard
        proxy._routable_at_fail = proxy._routable
        ops = sh.pending + [["fail", proxy.ci, now]]
        sh.pending = []
        kwargs = {"advance": sh.owed, "ops": ops, "step": False}
        sh.owed = 0
        self._send(sh, _WINDOW, kwargs)
        result = self._collect(sh)
        self._absorb(sh, result)
        proxy.alive = False
        proxy._died_at = self._boundaries
        for ci, reqs in result.get("displaced", ()):
            if ci == proxy.ci:
                return [TraceRequest.from_dict(d) for d in reqs]
        return []

    def _restore_cell(self, proxy: CellProxy, now: float) -> None:
        self._enqueue(proxy, ["restore", proxy.ci, now])
        proxy.alive = True
        # frozen while dead in BOTH drivers: the missed boundaries
        # become debt so autoscaler cadence lands identically
        proxy.tick_debt += self._boundaries - proxy._died_at
        # cell.restore heals every replica; membership cannot have
        # changed while dead (dead cells are never stepped)
        proxy._routable = proxy._routable_at_fail
        proxy._quiescent = True

    # -- the sharded loop ---------------------------------------------

    def _absorb(self, sh: _ShardHandle, result: dict) -> None:
        for ci, snap in result["cells"]:
            p = self._proxies[ci]
            p._out = snap["out"]
            p._admits = 0
            p._routable = snap["routable"]
            p._quiescent = snap["quiescent"]
        d = result["due"]
        sh.due = (bool(d["immediate"]),
                  _INF if d["ge"] is None else d["ge"],
                  _INF if d["cover"] is None else d["cover"])

    def _eval_due(self, proxy: CellProxy) -> bool:
        """Mirror of the pre-step ``_ticks % _eval_ticks == 0``
        check in fleet/sim.py: this cell's tick index is
        B - tick_debt (boundaries completed minus boundaries missed
        while dead)."""
        return ((self._boundaries - proxy.tick_debt)
                % self._eval_ticks == 0)

    def _step_boundary(self, now: float, tick: float) -> None:
        autoscale = self.cfg.autoscale
        todo = []
        for sh in self._shards:
            need = bool(sh.pending)
            if not need:
                im, ge, cover = sh.due
                need = im or ge <= now or cover <= now + tick
            if not need and autoscale:
                for ci in sh.cis:
                    p = self._proxies[ci]
                    if p.alive and self._eval_due(p):
                        need = True
                        break
            if need:
                todo.append(sh)
        if not todo:
            return
        for sh in todo:
            kwargs = {"advance": sh.owed, "ops": sh.pending,
                      "step": True}
            sh.owed = 0
            sh.pending = []
            self._send(sh, _WINDOW, kwargs)
        recs: List[dict] = []
        for sh in todo:
            result = self._collect(sh)
            self._absorb(sh, result)
            recs.extend(result["completions"])
        # lockstep observes completions per boundary, cells in name
        # order, hook-call order within a cell; a stable sort of the
        # per-shard buffers by global cell index reproduces it
        recs.sort(key=lambda r: r["ci"])
        for rec in recs:
            self._hooks[rec["ci"]](rec["entry"], _Comp(rec))

    def _advance_sharded(self, tick: float) -> None:
        """The ``_advance`` + ``_skip_uninteresting`` mirror: the
        identical dense-path exits and skip-loop break conditions,
        fed from cached shard DueSets and the parent tick mirror
        (no per-boundary scan backoff — an extra stepped boundary
        is semantically invisible, so the heuristic need not be
        replicated)."""
        self._boundaries += 1
        self.clock.advance(tick)
        for sh in self._shards:
            sh.owed += 1
        b = self.clock.now()
        if self._arrivals and self._arrivals[0][0].arrival_s <= b:
            return
        if self.chaos_events and self.chaos_events[0].at_s <= b:
            return
        if self.frontdoor.queue:
            return
        due_im = False
        due_ge = _INF
        due_cover = _INF
        if self._arrivals:
            due_ge = min(due_ge, self._arrivals[0][0].arrival_s)
        if self.chaos_events:
            due_ge = min(due_ge, self.chaos_events[0].at_s)
        for sh in self._shards:
            im, ge, cover = sh.due
            due_im = due_im or im
            due_ge = min(due_ge, ge)
            due_cover = min(due_cover, cover)
        evals_away = -1
        if self.cfg.autoscale:
            e = self._eval_ticks
            base = self._boundaries
            for p in self._proxies:
                if p.alive:
                    away = (e - ((base - p.tick_debt) % e)) % e
                    if evals_away < 0 or away < evals_away:
                        evals_away = away
        if due_im or evals_away == 0:
            return
        limit = self.cfg.max_virtual_s
        adv = self.clock.advance
        nowf = self.clock.now
        shards = self._shards
        skipped = 0
        while True:
            bb = nowf()
            if bb > limit or due_ge <= bb or due_cover <= bb + tick:
                break
            adv(tick)
            self._boundaries += 1
            for sh in shards:
                sh.owed += 1
            skipped += 1
            if evals_away > 0:
                evals_away -= 1
                if evals_away == 0:
                    break
        self.ev_skipped += skipped

    def run(self) -> Dict[str, object]:
        board_before = metrics.globe_board().counts()
        tick = resolve_tick_s(self.cfg.tick_s)
        for zone, reqs in self.traces.items():
            for req in reqs:
                self._origin[req.request_id] = zone
        try:
            while True:
                now = self.clock.now()
                if now > self.cfg.max_virtual_s:
                    break
                self._apply_chaos(now)
                while (self._arrivals
                       and self._arrivals[0][0].arrival_s <= now):
                    req, origin = self._arrivals.popleft()
                    shed = self.frontdoor.offer(req, origin, now)
                    if shed is not None:
                        self._record_frontdoor_shed(req, origin,
                                                    now)
                self.frontdoor.pump(now)
                self._step_boundary(now, tick)
                if self._done():
                    break
                self._advance_sharded(tick)
            self._fetch_reports()
        finally:
            self.close()
        self.log.sort(key=lambda e: (e["finish_s"],
                                     e["request_id"]))
        return self._report(board_before)

    def _fetch_reports(self) -> None:
        for sh in self._shards:
            self._send(sh, _REPORT, {})
        for sh in self._shards:
            for ci, rep in self._collect(sh):
                self._proxies[ci]._report = rep

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        for sh in self._shards:
            try:
                sh.proc.shutdown(grace_s=0.5)
            except Exception:
                pass

    def __del__(self):  # best-effort; run() closes on all paths
        try:
            self.close()
        except Exception:
            pass
