"""Device-level tracing/profiling for the simulated TPU stack.

SURVEY.md §5: the reference's only observability is echo lines — its
TPU build should time itself. The orchestrator side is covered by
`metrics.PhaseTimer` (create-pipeline phases); this module covers the
workload side with `jax.profiler`:

* `trace(log_dir)` — capture an XLA/device trace of a code region
  (TensorBoard-loadable xplane.pb + Chrome trace.json.gz);
* `capture(fn, *args)` — warm, then trace exactly one call;
* `summarize(log_dir)` — dependency-free top-ops table parsed from the
  Chrome trace (no tensorboard needed), preferring device-track events
  when the platform separates them;
* `profile_flagship()` — one traced flagship-model step, the workload
  the `profile` CLI subcommand ships.
"""

from __future__ import annotations

import contextlib
import glob
import gzip
import json
import os
import pathlib
import threading
import time
from typing import Any, Dict, List, MutableMapping, Optional

_SECTION_LOCK = threading.Lock()


@contextlib.contextmanager
def stopwatch(name: str, store: MutableMapping, ndigits: int = 1):
    """Record a section's wall seconds into ``store[name]``.

    The shared section accountant for bench.py/CLI phase attribution:
    thread-safe (the overlapped bring-up records control-plane and
    worker-pool sections from different threads) and exception-safe
    (a failing section still reports how long it burned)."""
    t0 = time.monotonic()
    try:
        yield
    finally:
        elapsed = round(time.monotonic() - t0, ndigits)
        with _SECTION_LOCK:
            store[name] = elapsed


def record_section(name: str, seconds: float, store: MutableMapping,
                   ndigits: int = 3) -> None:
    """Thread-safe store of an externally-measured section time."""
    with _SECTION_LOCK:
        store[name] = round(seconds, ndigits)


@contextlib.contextmanager
def trace(log_dir):
    """jax.profiler.trace with the directory created up front."""
    import jax

    path = pathlib.Path(log_dir)
    path.mkdir(parents=True, exist_ok=True)
    with jax.profiler.trace(str(path)):
        yield path


def annotation(name: str):
    """Named region that shows up on the trace timeline."""
    import jax

    return jax.profiler.TraceAnnotation(name)


def capture(fn, *args, log_dir, warmup: int = 1,
            label: str = "captured-step") -> Dict[str, Any]:
    """Run `fn(*args)` once under the tracer (after `warmup` untraced
    calls so compilation stays off the timeline); returns a report."""
    import jax

    for _ in range(max(0, warmup)):
        jax.block_until_ready(fn(*args))
    t0 = time.monotonic()
    with trace(log_dir) as path:
        with annotation(label):
            jax.block_until_ready(fn(*args))
    elapsed = time.monotonic() - t0
    return {
        "log_dir": str(path),
        "wall_s": round(elapsed, 4),
        "trace_files": [os.path.basename(p) for p in
                        _trace_files(path)],
    }


def _trace_files(log_dir) -> List[str]:
    return sorted(
        glob.glob(str(pathlib.Path(log_dir) /
                      "**" / "*.trace.json.gz"), recursive=True),
        key=os.path.getmtime,
    )


def summarize(log_dir, top: int = 10) -> Dict[str, Any]:
    """Top ops by total duration from the newest Chrome trace.

    Prefers events on device tracks (process name contains 'device:',
    as on TPU); host-only platforms (CPU) fall back to all non-Python
    events. Durations are microseconds.
    """
    files = _trace_files(log_dir)
    if not files:
        raise FileNotFoundError(f"no trace under {log_dir}")
    with gzip.open(files[-1], "rt") as fh:
        events = json.load(fh).get("traceEvents", [])

    process_names: Dict[int, str] = {}
    for ev in events:
        if ev.get("ph") == "M" and ev.get("name") == "process_name":
            process_names[ev.get("pid")] = ev["args"].get("name", "")
    device_pids = {
        pid for pid, name in process_names.items()
        if "device:" in name.lower()
    }

    def aggregate(device_only: bool) -> Dict[str, List[float]]:
        totals: Dict[str, List[float]] = {}
        for ev in events:
            if ev.get("ph") != "X" or not ev.get("dur"):
                continue
            name = ev.get("name", "")
            if device_only:
                if ev.get("pid") not in device_pids:
                    continue
            elif name.startswith("$"):  # python frame, host traces
                continue
            bucket = totals.setdefault(name, [0.0, 0])
            bucket[0] += ev["dur"]
            bucket[1] += 1
        return totals

    use_device = bool(device_pids)
    totals = aggregate(use_device)
    if use_device and not totals:
        # Device tracks registered but carried no ops (e.g. a platform
        # plugin that initialized without executing) — fall back to
        # host events rather than print an empty table.
        use_device = False
        totals = aggregate(False)

    ranked = sorted(totals.items(), key=lambda kv: -kv[1][0])[:top]
    return {
        "trace_file": files[-1],
        "device_tracks": use_device,
        "top_ops": [
            {"name": name, "total_us": round(total, 1), "count": count}
            for name, (total, count) in ranked
        ],
    }


def profile_flagship(log_dir, cfg=None, batch: int = 2,
                     top: int = 10) -> Dict[str, Any]:
    """Trace one jitted flagship forward+loss step and summarize it."""
    import jax

    from kind_tpu_sim.models import transformer as tf

    cfg = cfg or tf.ModelConfig()
    params = tf.init_params(jax.random.PRNGKey(0), cfg)
    tokens = tf.sample_batch(jax.random.PRNGKey(1), cfg, batch,
                             cfg.max_seq)
    step = jax.jit(lambda p, t: tf.loss_fn(p, t, cfg))
    report = capture(step, params, tokens, log_dir=log_dir,
                     label="flagship-loss-step")
    report["summary"] = summarize(log_dir, top=top)
    report["model"] = f"d{cfg.d_model}xL{cfg.n_layers}"
    return report
