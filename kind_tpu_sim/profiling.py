"""Device-level tracing/profiling for the simulated TPU stack.

SURVEY.md §5: the reference's only observability is echo lines — its
TPU build should time itself. The orchestrator side is covered by
`metrics.PhaseTimer` (create-pipeline phases); this module covers the
workload side with `jax.profiler`:

* `trace(log_dir)` — capture an XLA/device trace of a code region
  (TensorBoard-loadable xplane.pb + Chrome trace.json.gz);
* `capture(fn, *args)` — warm, then trace exactly one call;
* `summarize(log_dir)` — dependency-free top-ops table parsed from the
  Chrome trace (no tensorboard needed), preferring device-track events
  when the platform separates them;
* `profile_flagship()` — one traced flagship-model step, the workload
  the `profile` CLI subcommand ships.
"""

from __future__ import annotations

import contextlib
import glob
import gzip
import json
import os
import pathlib
import threading
import time
from typing import Any, Dict, List, MutableMapping, Optional

_SECTION_LOCK = threading.Lock()


@contextlib.contextmanager
def stopwatch(name: str, store: MutableMapping, ndigits: int = 1):
    """Record a section's wall seconds into ``store[name]``.

    The shared section accountant for bench.py/CLI phase attribution:
    thread-safe (the overlapped bring-up records control-plane and
    worker-pool sections from different threads) and exception-safe
    (a failing section still reports how long it burned)."""
    t0 = time.monotonic()
    try:
        yield
    finally:
        elapsed = round(time.monotonic() - t0, ndigits)
        with _SECTION_LOCK:
            store[name] = elapsed


def record_section(name: str, seconds: float, store: MutableMapping,
                   ndigits: int = 3) -> None:
    """Thread-safe store of an externally-measured section time."""
    with _SECTION_LOCK:
        store[name] = round(seconds, ndigits)


@contextlib.contextmanager
def trace(log_dir):
    """jax.profiler.trace with the directory created up front."""
    import jax

    path = pathlib.Path(log_dir)
    path.mkdir(parents=True, exist_ok=True)
    with jax.profiler.trace(str(path)):
        yield path


def annotation(name: str):
    """Named region that shows up on the trace timeline."""
    import jax

    return jax.profiler.TraceAnnotation(name)


def capture(fn, *args, log_dir, warmup: int = 1,
            label: str = "captured-step") -> Dict[str, Any]:
    """Run `fn(*args)` once under the tracer (after `warmup` untraced
    calls so compilation stays off the timeline); returns a report."""
    import jax

    for _ in range(max(0, warmup)):
        jax.block_until_ready(fn(*args))
    t0 = time.monotonic()
    with trace(log_dir) as path:
        with annotation(label):
            jax.block_until_ready(fn(*args))
    elapsed = time.monotonic() - t0
    return {
        "log_dir": str(path),
        "wall_s": round(elapsed, 4),
        "trace_files": [os.path.basename(p) for p in
                        _trace_files(path)],
    }


# fleet-sim event lanes -> the driver methods that process them
# (fleet/events.py LANE_* order). Costs are attributed by summing
# cProfile SELF time (tottime) over each lane's handlers — exclusive
# time never double-counts a lane even where handlers nest (e.g.
# _handle_completion -> _maybe_retry).
_FLEET_LANE_FNS = {
    "arrival": ("_offer_arrival", "_on_place"),
    "completion": ("_handle_completion", "_record", "_fire_hedges",
                   "_maybe_retry", "_on_prefill_done"),
    "chaos": ("_apply_chaos", "_apply_node_chaos",
              "_apply_link_chaos", "_apply_disagg_chaos"),
    "health_probe": ("_probe_quarantined", "_observe_health",
                     "_drain_migrations", "_refresh_link_slowdowns"),
    "autoscaler": ("_autoscale", "_autoscale_pools", "_sched_step"),
    "kv_transfer": ("displace_disagg", "_requeue_front"),
    "core": ("step", "run", "_skip_uninteresting", "_advance",
             "_next_wake", "quiescent"),
}


def profile_fleet_run(sim, top: int = 25) -> Dict[str, Any]:
    """Run ``sim.run()`` under cProfile; returns ``{"report", ...}``
    plus the opt-in `fleet run --profile` extras: wall seconds,
    events/s, the top functions by cumulative time, per-event-lane
    push counts (summed over the sim's EventHeap lanes — retry,
    hedge, KV, warm-up, rebind), and per-lane self-time costs
    attributed via :data:`_FLEET_LANE_FNS`. Wall-clock by design:
    nothing here feeds the seeded report, which stays byte-identical
    to an unprofiled run."""
    import cProfile
    import pstats

    from kind_tpu_sim.fleet import events as _ev

    prof = cProfile.Profile()
    t0 = time.monotonic()
    prof.enable()
    report = sim.run()
    prof.disable()
    wall = max(time.monotonic() - t0, 1e-9)

    stats = pstats.Stats(prof)
    lane_self_s = {lane: 0.0 for lane in _FLEET_LANE_FNS}
    fn_to_lane = {fn: lane for lane, fns in _FLEET_LANE_FNS.items()
                  for fn in fns}
    rows = []
    for (fname, lineno, func), (cc, nc, tt, ct, _callers) \
            in stats.stats.items():
        if "kind_tpu_sim" in fname:
            lane = fn_to_lane.get(func)
            if lane is not None:
                lane_self_s[lane] += tt
        rows.append({"function": f"{os.path.basename(fname)}:"
                                 f"{lineno}({func})",
                     "calls": nc, "self_s": round(tt, 4),
                     "cumulative_s": round(ct, 4)})
    rows.sort(key=lambda r: -r["cumulative_s"])

    lane_names = {_ev.LANE_ARRIVAL: "arrival",
                  _ev.LANE_COMPLETION: "completion",
                  _ev.LANE_CHAOS: "chaos",
                  _ev.LANE_HEALTH_PROBE: "health_probe",
                  _ev.LANE_AUTOSCALER: "autoscaler",
                  _ev.LANE_PLANNER: "planner",
                  _ev.LANE_KV_TRANSFER: "kv_transfer"}
    pushes = {name: 0 for name in lane_names.values()}
    for heap in (sim._retry_heap, sim._hedge_heap, sim._kv_heap,
                 sim._warming, sim._rebinding):
        for lane, seq in enumerate(heap._seq):
            pushes[lane_names[lane]] += seq
    # the two lanes that never ride a heap: offered arrivals and
    # delivered completions come straight off the trace/replicas
    pushes["arrival"] += report.get("requests", 0)
    pushes["completion"] += len(report.get("completions", ()))

    lanes = {
        name: {"events": pushes.get(name, 0),
               "self_s": round(lane_self_s.get(name, 0.0), 4)}
        for name in sorted(set(pushes) | set(lane_self_s))
    }
    return {
        "report": report,
        "wall_s": round(wall, 3),
        "events_per_s": round(len(report.get("completions", ()))
                              / wall),
        "lanes": lanes,
        "top_functions": rows[:top],
    }


def _trace_files(log_dir) -> List[str]:
    return sorted(
        glob.glob(str(pathlib.Path(log_dir) /
                      "**" / "*.trace.json.gz"), recursive=True),
        key=os.path.getmtime,
    )


def summarize(log_dir, top: int = 10) -> Dict[str, Any]:
    """Top ops by total duration from the newest Chrome trace.

    Prefers events on device tracks (process name contains 'device:',
    as on TPU); host-only platforms (CPU) fall back to all non-Python
    events. Durations are microseconds.
    """
    files = _trace_files(log_dir)
    if not files:
        raise FileNotFoundError(f"no trace under {log_dir}")
    with gzip.open(files[-1], "rt") as fh:
        events = json.load(fh).get("traceEvents", [])

    process_names: Dict[int, str] = {}
    for ev in events:
        if ev.get("ph") == "M" and ev.get("name") == "process_name":
            process_names[ev.get("pid")] = ev["args"].get("name", "")
    device_pids = {
        pid for pid, name in process_names.items()
        if "device:" in name.lower()
    }

    def aggregate(device_only: bool) -> Dict[str, List[float]]:
        totals: Dict[str, List[float]] = {}
        for ev in events:
            if ev.get("ph") != "X" or not ev.get("dur"):
                continue
            name = ev.get("name", "")
            if device_only:
                if ev.get("pid") not in device_pids:
                    continue
            elif name.startswith("$"):  # python frame, host traces
                continue
            bucket = totals.setdefault(name, [0.0, 0])
            bucket[0] += ev["dur"]
            bucket[1] += 1
        return totals

    use_device = bool(device_pids)
    totals = aggregate(use_device)
    if use_device and not totals:
        # Device tracks registered but carried no ops (e.g. a platform
        # plugin that initialized without executing) — fall back to
        # host events rather than print an empty table.
        use_device = False
        totals = aggregate(False)

    ranked = sorted(totals.items(), key=lambda kv: -kv[1][0])[:top]
    return {
        "trace_file": files[-1],
        "device_tracks": use_device,
        "top_ops": [
            {"name": name, "total_us": round(total, 1), "count": count}
            for name, (total, count) in ranked
        ],
    }


def profile_flagship(log_dir, cfg=None, batch: int = 2,
                     top: int = 10) -> Dict[str, Any]:
    """Trace one jitted flagship forward+loss step and summarize it."""
    import jax

    from kind_tpu_sim.models import transformer as tf

    cfg = cfg or tf.ModelConfig()
    params = tf.init_params(jax.random.PRNGKey(0), cfg)
    tokens = tf.sample_batch(jax.random.PRNGKey(1), cfg, batch,
                             cfg.max_seq)
    step = jax.jit(lambda p, t: tf.loss_fn(p, t, cfg))
    report = capture(step, params, tokens, log_dir=log_dir,
                     label="flagship-loss-step")
    report["summary"] = summarize(log_dir, top=top)
    report["model"] = f"d{cfg.d_model}xL{cfg.n_layers}"
    return report
