"""Version-compatibility shims over the jax/jaxlib surface.

The simulator targets the jax 0.9.x API (top-level ``jax.shard_map``,
``jaxlib._jax``, dict-valued ``cost_analysis``) but must also run on
the 0.4.x line some hosts ship, where the same surfaces live under
``jax.experimental.shard_map`` / ``jaxlib.xla_extension`` and
``cost_analysis`` returns a one-element list. Every shim resolves the
modern name first so on a current jax this module is a no-op pass-
through; nothing here changes behavior, only where a name is found.

Import stays lazy (functions, not module-level ``import jax``) for the
same reason the rest of the tree imports jax inside functions: the
orchestrator/topology layers must work with no jax installed at all.
"""

from __future__ import annotations


def ensure_shard_map():
    """Return ``jax.shard_map``, installing it from
    ``jax.experimental.shard_map`` on jax versions that predate the
    top-level export. Call after ``import jax``, before the first
    ``jax.shard_map(...)`` use; idempotent."""
    import jax

    try:
        return jax.shard_map
    except AttributeError:
        pass
    from jax.experimental.shard_map import shard_map

    # Top-level alias so existing `jax.shard_map(...)` call sites work
    # unchanged; module attribute assignment bypasses jax's
    # deprecation __getattr__, so the alias wins on later lookups.
    jax.shard_map = shard_map
    return shard_map


def pvary(x, axis_name):
    """Mark ``x`` device-varying over ``axis_name`` inside a
    shard_map body, across the API generations: ``jax.lax.pcast``
    (0.9+), ``jax.lax.pvary`` (0.5-0.8). Pre-varying-manifest jax
    (0.4.x) needs no cast at all — replicated operands are accepted
    by the collectives — so identity is the correct fallback."""
    import jax

    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, axis_name=axis_name, to="varying")
    if hasattr(jax.lax, "pvary"):
        return jax.lax.pvary(x, axis_name)
    return x


def jaxlib_extension():
    """The jaxlib C-extension module under either layout:
    ``jaxlib._jax`` (0.5+) or ``jaxlib.xla_extension`` (0.4.x).
    Returns None when neither import resolves."""
    try:
        import jaxlib._jax as ext  # noqa: F401 - jax >= 0.5 layout

        return ext
    except ImportError:
        pass
    try:
        import jaxlib.xla_extension as ext  # 0.4.x layout

        return ext
    except ImportError:
        return None


def jaxlib_extension_name() -> str:
    """The import path :func:`jaxlib_extension` resolved (for error
    messages naming what is actually installed)."""
    ext = jaxlib_extension()
    return ext.__name__ if ext is not None else "jaxlib._jax"


def cost_analysis_dict(compiled) -> dict:
    """``compiled.cost_analysis()`` as a flat dict on every jax line:
    0.4.x returns a one-element list of per-computation dicts, 0.5+
    returns the dict directly."""
    cost = compiled.cost_analysis()
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    return dict(cost or {})
