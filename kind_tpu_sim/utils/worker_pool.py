"""Persistent JAX worker pool — the simulator's warm-path runtime.

The bench/CLI cold path used to pay `import jax` + backend init +
XLA compile in a fresh subprocess for EVERY JAX-touching phase
(r05: 99.7% of the stack-ready headline was one cold subprocess).
This module makes that cost once-per-session: long-lived CPU-backend
Python workers, preforked once per bench/CLI process, that callers
submit JAX jobs to over a small length-prefixed JSON protocol on the
worker's stdin/stdout pipes.

Protocol (both directions): 4-byte big-endian length, then a UTF-8
JSON object. The worker's FIRST frame is a hello carrying its pid and
(when preforked warm) the measured warm-up seconds; every later frame
answers exactly one request, in order:

    request:  {"id": 3, "job": "psum_smoke", "kwargs": {...}}
    response: {"id": 3, "ok": true, "result": {...}, "elapsed_s": 0.04}

The worker rebinds its real stdout to stderr before serving, so stray
prints (jax warnings, absl logs) can never corrupt the framing.

Failure contract: a job that raises inside the worker returns
``ok: false`` and surfaces as :class:`JobError` (no respawn — the
worker is still healthy). A worker that DIES mid-job (EOF on the
pipe) is respawned and the job retried once; a second death raises
:class:`WorkerCrash` with the worker's stderr tail. A job deadline
kills the (possibly wedged) worker and raises ``TimeoutError``
without retrying — retrying a timeout would double the wait.

Two spawn temperatures:

* warm (default) — the worker imports jax and initializes the
  backend immediately at spawn; the hello reports ``warm_s``.
  bench.py overlaps this warm-up with the orchestrator/plugin
  bring-up phases.
* cold (``warm=False`` / :func:`run_grid`) — a bare protocol loop
  with nothing imported; used by the multihost slice driver, whose
  workers must set per-process identity env before jax ever loads.

Workers inherit :func:`kind_tpu_sim.utils.shell.cpu_subprocess_env`,
so the persistent XLA compilation cache (``.cache/jax``) is wired in
for every pooled job too.
"""

from __future__ import annotations

import json
import logging
import os
import pathlib
import queue
import selectors
import struct
import subprocess
import sys
import tempfile
import threading
import time
from concurrent.futures import Future
from typing import Dict, List, Optional, Sequence

from kind_tpu_sim.analysis import knobs

log = logging.getLogger("kind-tpu-sim")

WARM_ENV = knobs.POOL_WARM

# Injectable chaos fault for a protocol worker (docs/CHAOS.md,
# docs/HEALTH.md): "crash@N" kills the worker (os._exit) when it
# RECEIVES its Nth request (1-based); "hang@N:S" sleeps S seconds
# before answering it. Two GRAY (sub-crash) kinds join them:
# "slow@N:S" stalls S seconds before answering EVERY request from the
# Nth on (a persistent straggler — alive, correct, slow), and
# "flaky@K:S" stalls S seconds before answering every Kth request (an
# intermittently-stalling node). The parent strips this variable when
# it respawns a worker, so an injected fault is transient by
# construction — exactly the failure the recovery paths (respawn+
# retry, cell requeue, deadline kill, straggler quarantine +
# speculative re-dispatch) exist for.
CHAOS_FAULT_ENV = knobs.CHAOS_FAULT

# A frame bigger than this is protocol corruption, not data.
MAX_FRAME_BYTES = 64 * 1024 * 1024

# Bulk-payload transport (KIND_TPU_SIM_POOL_SHM, default on): each
# worker gets two parent-OWNED multiprocessing.shared_memory
# segments (one per direction). A payload at least SHM_MIN_BYTES
# long travels as raw bytes in the segment plus a tiny {"shm_len": N}
# control frame; smaller payloads (and anything when the knob is
# off, the segment is missing, or the payload outgrows the segment)
# stay in-band. The request/response protocol is strictly serialized
# per worker, so one segment per direction needs no further locking,
# and the PARENT creates and unlinks both segments — a crashed or
# deadline-killed worker can never leak one.
POOL_SHM_BYTES = 32 * 1024 * 1024
SHM_MIN_BYTES = 64 * 1024

REPO_ROOT = pathlib.Path(__file__).resolve().parents[2]


class JobError(RuntimeError):
    """The job raised inside the worker (worker itself is healthy)."""

    def __init__(self, message: str, remote_traceback: str = ""):
        super().__init__(message)
        self.remote_traceback = remote_traceback


class WorkerCrash(RuntimeError):
    """The worker process died before answering."""


class WorkerCancelled(RuntimeError):
    """The caller cancelled a pending read (e.g. the grid finished
    through a speculative copy while a straggler still held the
    original dispatch) — not a worker failure."""


class FrameError(RuntimeError):
    """Length-prefix framing violation (an implausible declared
    length) — protocol corruption, not data."""


# ---------------------------------------------------------------------
# framing — ONE parser for both sides of the pipe: the blocking
# stream reader (worker side) and the incremental buffer splitter
# (parent side) share frame_length below, so the protocol has a
# single point of truth for the header format and the size bound.


def frame_length(header: bytes) -> int:
    """Decode and validate a 4-byte big-endian frame header."""
    (length,) = struct.unpack(">I", header)
    if length > MAX_FRAME_BYTES:
        raise FrameError(f"implausible frame length {length}")
    return length


def write_frame(stream, obj) -> None:
    payload = json.dumps(obj, sort_keys=True).encode("utf-8")
    stream.write(struct.pack(">I", len(payload)) + payload)
    stream.flush()


def read_frame(stream):
    """Blocking frame read from a binary stream; None on clean EOF."""
    header = stream.read(4)
    if not header:
        return None
    if len(header) < 4:
        raise EOFError("truncated frame header")
    try:
        length = frame_length(header)
    except FrameError as exc:
        raise EOFError(str(exc)) from exc
    payload = b""
    while len(payload) < length:
        chunk = stream.read(length - len(payload))
        if not chunk:
            raise EOFError("truncated frame payload")
        payload += chunk
    return json.loads(payload.decode("utf-8"))


# ---------------------------------------------------------------------
# worker side


def _warmup() -> dict:
    """Import jax and initialize the backend (the once-per-session
    cost the pool exists to amortize)."""
    import jax

    platforms = os.environ.get("JAX_PLATFORMS")
    if platforms:
        # Pin the config even when a site plugin (axon) registers
        # itself regardless of the env var — same defense as
        # tests/conftest.py.
        jax.config.update("jax_platforms", platforms)
    return {
        "devices": jax.device_count(),
        "backend": jax.default_backend(),
        "jax_version": jax.__version__,
    }


def _job_ping() -> dict:
    return {"pid": os.getpid()}


def _job_psum_smoke(topology: str = "2x4",
                    expect_devices: Optional[int] = None) -> dict:
    """The BASELINE acceptance gate: all advertised fake chips visible
    and a psum verified over them."""
    info = _warmup()
    if expect_devices is not None and info["devices"] != expect_devices:
        raise RuntimeError(
            f"{info['devices']} devices visible, expected "
            f"{expect_devices}")
    from kind_tpu_sim import topology as topo
    from kind_tpu_sim.parallel import collectives, mesh

    report = collectives.psum_smoke(
        mesh.slice_mesh(topo.make_slice(topology=topology)))
    if not report.get("ok"):
        raise RuntimeError(f"psum smoke failed: {report}")
    report["worker_pid"] = os.getpid()
    return report


def _job_collectives_suite(topology: str = "2x4") -> dict:
    info = _warmup()
    from kind_tpu_sim import topology as topo
    from kind_tpu_sim.parallel import collectives, mesh

    report = collectives.run_all(
        mesh.slice_mesh(topo.make_slice(topology=topology)))
    report["devices"] = info["devices"]
    report["worker_pid"] = os.getpid()
    return report


def _job_call(target: str, kwargs: Optional[dict] = None):
    """Generic job: ``module.path:attr`` resolved and called in the
    (warm) worker — how bench.py runs the ring bench and the multihost
    grid runs its per-host report without a bespoke job each."""
    import importlib

    mod_name, _, attr_path = target.partition(":")
    if not attr_path:
        raise ValueError(f"target {target!r} must be 'module:attr'")
    obj = importlib.import_module(mod_name)
    for attr in attr_path.split("."):
        obj = getattr(obj, attr)
    return obj(**(kwargs or {}))


def _job_call_batch(target: str,
                    kwargs_list: Sequence[dict]) -> list:
    """N generic calls in one protocol round trip — the batched cell
    dispatch the grid schedulers use to amortize framing + dispatch
    overhead when cells are cheap. Each call is the same pure
    function of its kwargs as a lone ``call``, so results are
    position-identical to N single dispatches."""
    return [_job_call(target, kw) for kw in kwargs_list]


def _job_psum_cache_probe(topology: str = "2x4") -> dict:
    """psum smoke + XLA persistent-cache hit/miss counters.

    The diagnostic behind the warm-path story: a first-ever run
    reports misses (the cache is being populated), a later worker on
    the same cache dir reports hits (the compile was skipped). Must
    run before any other compile in this worker so the counters
    belong to the smoke alone."""
    import jax

    counts = {"cache_hits": 0, "cache_misses": 0}

    def listener(event: str, **kw) -> None:
        for key in counts:
            if event.endswith(key):
                counts[key] += 1

    jax.monitoring.register_event_listener(listener)
    report = _job_psum_smoke(topology=topology)
    report.update(counts)
    report["cache_enabled"] = bool(
        os.environ.get("JAX_COMPILATION_CACHE_DIR"))
    return report


def _job_crash(code: int = 13) -> None:
    """Die without answering — the chaos hook the crash-recovery
    tests (and `chaos`-minded users) exercise the respawn path with."""
    os._exit(code)


def _job_hang(seconds: float = 3600.0) -> dict:
    """Wedge without answering for ``seconds`` — the chaos hook for
    the deadline-kill path (the parent must TimeoutError and kill,
    never wait out a hung worker)."""
    time.sleep(seconds)
    return {"slept_s": seconds}


JOBS = {
    "ping": _job_ping,
    "warmup": _warmup,
    "psum_smoke": _job_psum_smoke,
    "psum_cache_probe": _job_psum_cache_probe,
    "collectives_suite": _job_collectives_suite,
    "call": _job_call,
    "call_batch": _job_call_batch,
    "crash": _job_crash,
    "hang": _job_hang,
}


def _attach_shm(name: str):
    """Attach a parent-owned segment by name; None when anything is
    off (the knob, the platform, a stale name) — the pipe framing is
    always a complete fallback. The attachment is unregistered from
    the child's resource_tracker: the PARENT owns segment lifetime,
    and a tracked attachment would double-unlink at child exit."""
    try:
        from multiprocessing import resource_tracker, shared_memory

        seg = shared_memory.SharedMemory(name=name)
        try:
            resource_tracker.unregister(seg._name, "shared_memory")
        except Exception:
            pass
        return seg
    except Exception:
        return None


def _parse_fault(spec: Optional[str]):
    """CHAOS_FAULT_ENV spec -> (kind, request_no, param) or None.

    Formats: "crash@2" (exit on receiving request 2), "hang@1:30"
    (sleep 30s before answering request 1), "slow@1:0.5" (stall 0.5s
    before answering every request from the 1st on — a straggler),
    "flaky@3:0.5" (stall 0.5s before answering every 3rd request).
    Malformed specs are ignored — a chaos knob must never break a
    healthy worker."""
    if not spec or "@" not in spec:
        return None
    kind, _, rest = spec.partition("@")
    at, _, param = rest.partition(":")
    try:
        return kind, int(at), float(param or 0.0)
    except ValueError:
        return None


def _serve() -> int:
    """Worker main loop: hello, then answer requests until EOF."""
    import traceback

    # Bind the protocol to the ORIGINAL stdout, then point fd 1 at
    # stderr: later stray writes (warnings, absl) land in the log
    # channel instead of corrupting frames.
    proto_fd = os.dup(1)
    os.dup2(2, 1)
    out = os.fdopen(proto_fd, "wb")
    inp = sys.stdin.buffer

    # bulk transport: parent-owned segments, one per direction
    shm_in = shm_out = None
    segs = str(knobs.get(knobs.POOL_SHM_SEGS) or "")
    if segs and ":" in segs:
        in_name, _, out_name = segs.partition(":")
        shm_in = _attach_shm(in_name)
        shm_out = _attach_shm(out_name)

    def send(obj) -> None:
        payload = json.dumps(obj, sort_keys=True).encode("utf-8")
        if (shm_out is not None and len(payload) >= SHM_MIN_BYTES
                and len(payload) <= shm_out.size):
            shm_out.buf[:len(payload)] = payload
            write_frame(out, {"shm_len": len(payload)})
            return
        out.write(struct.pack(">I", len(payload)) + payload)
        out.flush()

    hello = {"hello": True, "pid": os.getpid()}
    if knobs.get(WARM_ENV):
        t0 = time.monotonic()
        try:
            hello.update(_warmup())
            hello["warm_s"] = round(time.monotonic() - t0, 3)
        except Exception as exc:  # surfaced to the parent, not fatal
            hello["warm_error"] = f"{type(exc).__name__}: {exc}"[:500]
    write_frame(out, hello)

    fault = _parse_fault(knobs.get(CHAOS_FAULT_ENV))
    req_no = 0
    while True:
        try:
            req = read_frame(inp)
        except EOFError:
            return 1
        if (isinstance(req, dict) and "shm_len" in req
                and shm_in is not None):
            # bulk request: the control frame only carries the length
            req = json.loads(
                bytes(shm_in.buf[:req["shm_len"]]).decode("utf-8"))
        if req is None or req.get("op") == "shutdown":
            return 0
        req_no += 1
        if fault is not None:
            kind, at, param = fault
            if req_no == at:
                if kind == "crash":
                    os._exit(int(param) or 13)
                if kind == "hang":
                    time.sleep(param or 3600.0)
            if kind == "slow" and req_no >= at:
                # gray straggler: every job from request `at` on is
                # stalled — the worker stays alive and correct
                time.sleep(param)
            if kind == "flaky" and at > 0 and req_no % at == 0:
                # intermittent sub-crash stall on every at-th request
                time.sleep(param)
        resp = {"id": req.get("id")}
        t0 = time.monotonic()
        try:
            job = JOBS[req["job"]]
            resp["result"] = job(**(req.get("kwargs") or {}))
            resp["ok"] = True
        except Exception as exc:
            resp["ok"] = False
            resp["error"] = f"{type(exc).__name__}: {exc}"[:2000]
            resp["traceback"] = traceback.format_exc()[-2000:]
        resp["elapsed_s"] = round(time.monotonic() - t0, 6)
        send(resp)


# ---------------------------------------------------------------------
# parent side


def _pool_child_env(extra_env: Optional[Dict[str, str]] = None,
                    warm: bool = True) -> Dict[str, str]:
    from kind_tpu_sim.utils.shell import cpu_subprocess_env

    env = cpu_subprocess_env()
    env.update(extra_env or {})
    env["PYTHONPATH"] = (str(REPO_ROOT) + os.pathsep
                         + env.get("PYTHONPATH", ""))
    env[WARM_ENV] = "1" if warm else "0"
    return env


def simulated_slice_env(chips: int = 8) -> Dict[str, str]:
    """Env for a worker simulating one host of a slice: CPU backend
    exposing ``chips`` virtual devices (the jax-tpu-pod trick)."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        flags = (flags
                 + f" --xla_force_host_platform_device_count={chips}"
                 ).strip()
    return {"JAX_PLATFORMS": "cpu", "XLA_FLAGS": flags}


class _WorkerProc:
    """One protocol worker process + its read buffer and stderr log."""

    def __init__(self, env: Dict[str, str],
                 stderr_path: Optional[pathlib.Path] = None):
        self._buf = b""
        self.hello: Optional[dict] = None
        self.spawned_at = time.monotonic()
        # bulk transport: the parent CREATES (and later unlinks) one
        # segment per direction and hands the worker the names — a
        # worker that crashes, hangs, or is deadline-killed cannot
        # leak a segment because it never owns one
        self._shm_in = self._shm_out = None
        if bool(knobs.get(knobs.POOL_SHM)):
            try:
                from multiprocessing import shared_memory

                self._shm_in = shared_memory.SharedMemory(
                    create=True, size=POOL_SHM_BYTES)
                self._shm_out = shared_memory.SharedMemory(
                    create=True, size=POOL_SHM_BYTES)
                env = dict(env)
                env[knobs.POOL_SHM_SEGS] = (
                    f"{self._shm_in.name}:{self._shm_out.name}")
            except Exception:  # no /dev/shm etc. — pipe fallback
                self._close_shm()
        if stderr_path is None:
            fd, name = tempfile.mkstemp(prefix="tpu-sim-worker-",
                                        suffix=".err")
            self.stderr_path = pathlib.Path(name)
            self._stderr_file = os.fdopen(fd, "wb")
            self._own_stderr = True
        else:
            self.stderr_path = stderr_path
            self._stderr_file = open(stderr_path, "wb")
            self._own_stderr = False
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "kind_tpu_sim.utils.worker_pool",
             "--serve"],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=self._stderr_file, env=env,
        )

    @property
    def pid(self) -> int:
        return self.proc.pid

    def alive(self) -> bool:
        return self.proc.poll() is None

    def stderr_tail(self, n: int = 2000) -> str:
        try:
            self._stderr_file.flush()
            return self.stderr_path.read_text(errors="replace")[-n:]
        except (OSError, ValueError):
            # ValueError: file already closed by kill()/close_files()
            # while a reader was still draining the stdout pipe
            return ""

    def read_frame(self, deadline: float, cancel=None):
        """One frame from the worker's stdout, or raise: WorkerCrash
        on EOF/death, TimeoutError past ``deadline``,
        WorkerCancelled when ``cancel`` (a threading.Event) is set —
        how a grid run stops waiting on a straggler whose cell a
        speculative copy already finished."""
        fd = self.proc.stdout.fileno()
        sel = selectors.DefaultSelector()
        sel.register(self.proc.stdout, selectors.EVENT_READ)
        poll_s = 1.0 if cancel is None else 0.05
        try:
            while True:
                frame, self._buf = _try_parse(self._buf)
                if frame is not None:
                    if (isinstance(frame, dict)
                            and "shm_len" in frame
                            and self._shm_out is not None):
                        # bulk response: payload sits in the
                        # worker->parent segment
                        n = frame["shm_len"]
                        frame = json.loads(bytes(
                            self._shm_out.buf[:n]).decode("utf-8"))
                    return frame
                if cancel is not None and cancel.is_set():
                    raise WorkerCancelled(
                        f"read from worker {self.pid} cancelled")
                remain = deadline - time.monotonic()
                if remain <= 0:
                    raise TimeoutError(
                        f"worker {self.pid} gave no answer in time")
                if not sel.select(timeout=min(remain, poll_s)):
                    if not self.alive():
                        raise WorkerCrash(
                            f"worker {self.pid} exited "
                            f"(rc={self.proc.returncode}): "
                            f"{self.stderr_tail()}")
                    continue
                data = os.read(fd, 65536)
                if not data:
                    raise WorkerCrash(
                        f"worker {self.pid} closed its pipe "
                        f"(rc={self.proc.poll()}): "
                        f"{self.stderr_tail()}")
                self._buf += data
        finally:
            sel.close()

    def ensure_ready(self, deadline: float) -> dict:
        if self.hello is None:
            self.hello = self.read_frame(deadline)
        return self.hello

    def send(self, req: dict) -> None:
        """One request toward the worker: big payloads go through
        the parent->worker segment, everything else in-band."""
        try:
            payload = json.dumps(
                req, sort_keys=True).encode("utf-8")
            if (self._shm_in is not None
                    and len(payload) >= SHM_MIN_BYTES
                    and len(payload) <= self._shm_in.size):
                self._shm_in.buf[:len(payload)] = payload
                write_frame(self.proc.stdin,
                            {"shm_len": len(payload)})
                return
            self.proc.stdin.write(
                struct.pack(">I", len(payload)) + payload)
            self.proc.stdin.flush()
        except (BrokenPipeError, OSError) as exc:
            raise WorkerCrash(
                f"worker {self.pid} pipe closed: {exc}; "
                f"{self.stderr_tail()}") from exc

    def request(self, req: dict, deadline: float,
                cancel=None) -> dict:
        self.ensure_ready(deadline)
        self.send(req)
        return self.read_frame(deadline, cancel=cancel)

    def kill(self) -> None:
        if self.alive():
            self.proc.kill()
        try:
            self.proc.wait(timeout=10)
        except subprocess.TimeoutExpired:  # pragma: no cover
            pass
        self.close_files()

    def shutdown(self, grace_s: float = 2.0) -> None:
        try:
            if self.alive():
                write_frame(self.proc.stdin, {"op": "shutdown"})
                self.proc.stdin.close()
                self.proc.wait(timeout=grace_s)
        except (OSError, subprocess.TimeoutExpired):
            pass
        self.kill()

    def close_files(self) -> None:
        self._close_shm()
        try:
            self._stderr_file.close()
        except OSError:  # pragma: no cover
            pass
        if self._own_stderr:
            try:
                self.stderr_path.unlink()
            except OSError:  # pragma: no cover
                pass

    def _close_shm(self) -> None:
        for seg in (self._shm_in, self._shm_out):
            if seg is None:
                continue
            try:
                seg.close()
            except Exception:  # pragma: no cover
                pass
            try:
                seg.unlink()
            except Exception:  # pragma: no cover
                pass
        self._shm_in = self._shm_out = None


# the single-worker surface other drivers build on (globe/shard.py
# runs its own session-oriented protocol over raw workers instead
# of the job-queue WorkerPool)
PoolWorker = _WorkerProc
pool_child_env = _pool_child_env


def _try_parse(buf: bytes):
    """(frame, rest) if ``buf`` holds a complete frame, else
    (None, buf). Corruption surfaces as WorkerCrash — on the parent
    side a worker talking garbage is indistinguishable from one
    dying mid-frame."""
    if len(buf) < 4:
        return None, buf
    try:
        length = frame_length(buf[:4])
    except FrameError as exc:
        raise WorkerCrash(str(exc)) from exc
    if len(buf) < 4 + length:
        return None, buf
    return json.loads(buf[4:4 + length].decode("utf-8")), buf[4 + length:]


_SHUTDOWN = object()


class WorkerPool:
    """Preforked protocol workers + a submit queue.

    ``submit_async`` returns a :class:`concurrent.futures.Future`;
    one dispatcher thread per worker drains the shared queue, so a
    pool of size N runs N jobs concurrently and a caller never blocks
    on spawn/warm-up unless it asks for a result.
    """

    def __init__(self, size: int = 1, warm: bool = True,
                 extra_env: Optional[Dict[str, str]] = None,
                 job_timeout: float = 300.0, health=None):
        # optional kind_tpu_sim.health.FailureDetector: the heartbeat
        # sweep reports per-slot liveness probes into it, and a dead
        # slot's respawn restores it (docs/HEALTH.md)
        self._health = health
        self._env = _pool_child_env(extra_env, warm=warm)
        self._timeout = job_timeout
        self._queue: "queue.Queue" = queue.Queue()
        self._lock = threading.Lock()
        self._next_id = 0
        self._closed = False
        self.respawns = 0
        self._procs: List[Optional[_WorkerProc]] = []
        self._threads: List[threading.Thread] = []
        # slots mid-job: the heartbeat must not touch them (their
        # dispatcher owns crash handling for the in-flight request)
        self._busy: List[bool] = [False] * size
        self._hb_stop = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None
        for slot in range(size):
            self._procs.append(_WorkerProc(self._env))
            thread = threading.Thread(
                target=self._dispatch, args=(slot,),
                name=f"tpu-sim-pool-{slot}", daemon=True)
            thread.start()
            self._threads.append(thread)

    # -- submission ---------------------------------------------------

    def submit_async(self, job: str, *, timeout: Optional[float] = None,
                     **kwargs) -> Future:
        if self._closed:
            raise RuntimeError("pool is closed")
        with self._lock:
            self._next_id += 1
            req_id = self._next_id
        req = {"id": req_id, "job": job, "kwargs": kwargs}
        fut: Future = Future()
        self._queue.put((fut, req, timeout or self._timeout))
        return fut

    def submit(self, job: str, *, timeout: Optional[float] = None,
               **kwargs):
        return self.submit_async(job, timeout=timeout,
                                 **kwargs).result()

    # -- introspection ------------------------------------------------

    def worker_pids(self) -> List[int]:
        return [p.pid for p in self._procs if p is not None]

    def bringup(self, timeout: float = 120.0) -> dict:
        """A ready worker's hello: pid, and for warm pools the
        measured ``warm_s`` (jax import + backend init) and device
        count."""
        info = dict(self.submit("ping", timeout=timeout))
        for proc in self._procs:
            if proc is not None and proc.hello:
                info.update(proc.hello)
                break
        return info

    # -- health -------------------------------------------------------

    def check_health(self) -> List[dict]:
        """One liveness row per slot: pid, alive, busy, uptime. The
        heartbeat's observable; also the cheap pre-flight a caller
        can make before a batch of submissions."""
        now = time.monotonic()
        out = []
        with self._lock:
            for slot, proc in enumerate(self._procs):
                out.append({
                    "slot": slot,
                    "pid": proc.pid if proc is not None else None,
                    "alive": bool(proc is not None and proc.alive()),
                    "busy": self._busy[slot],
                    "uptime_s": (round(now - proc.spawned_at, 3)
                                 if proc is not None else None),
                })
        return out

    def start_heartbeat(self, interval_s: float = 5.0) -> None:
        """Background liveness sweep: a dead IDLE worker is respawned
        proactively (instead of lazily at its next job), so a pool
        that sat through a chaos kill is warm again before the next
        submission. Busy slots are left to their dispatcher — its
        crash path owns the in-flight request."""
        if self._hb_thread is not None:
            return
        self._hb_stop.clear()

        def sweep() -> None:
            while not self._hb_stop.wait(interval_s):
                for slot in range(len(self._procs)):
                    with self._lock:
                        if self._busy[slot] or self._closed:
                            continue
                        proc = self._procs[slot]
                        alive = proc is not None and proc.alive()
                        if self._health is not None:
                            self._health.record_probe(
                                f"pool-{slot}", ok=alive,
                                now=time.monotonic())
                        if alive:
                            continue
                        self._respawn(slot, reason="heartbeat")
                        if self._health is not None:
                            self._health.restore(
                                f"pool-{slot}", time.monotonic(),
                                reason="respawned")

        self._hb_thread = threading.Thread(
            target=sweep, name="tpu-sim-pool-heartbeat", daemon=True)
        self._hb_thread.start()

    def stop_heartbeat(self) -> None:
        if self._hb_thread is None:
            return
        self._hb_stop.set()
        self._hb_thread.join(timeout=5)
        self._hb_thread = None

    # -- dispatch -----------------------------------------------------

    def _respawn(self, slot: int, reason: str = "crash") -> _WorkerProc:
        from kind_tpu_sim import metrics

        old = self._procs[slot]
        if old is not None:
            old.kill()
        self.respawns += 1
        # A respawn heals: the injected chaos fault (if any) applies
        # to the ORIGINAL worker only, so recovery converges instead
        # of re-crashing forever.
        env = dict(self._env)
        env.pop(CHAOS_FAULT_ENV, None)
        proc = _WorkerProc(env)
        self._procs[slot] = proc
        metrics.recovery_log().record(
            "worker_respawn", slot=slot, reason=reason, pid=proc.pid)
        return proc

    def _dispatch(self, slot: int) -> None:
        while True:
            item = self._queue.get()
            if item is _SHUTDOWN:
                return
            fut, req, timeout = item
            if not fut.set_running_or_notify_cancel():
                continue
            self._busy[slot] = True
            attempts_left = 1  # one respawn+retry per job
            while True:
                with self._lock:
                    proc = self._procs[slot]
                    if proc is None or not proc.alive():
                        proc = self._respawn(slot)
                deadline = time.monotonic() + timeout
                try:
                    resp = proc.request(req, deadline)
                except WorkerCrash as exc:
                    self._procs[slot] = None
                    proc.kill()
                    if attempts_left > 0:
                        attempts_left -= 1
                        log.warning(
                            "pool worker died (%s); respawning and "
                            "retrying job %s once", exc, req["job"])
                        continue
                    fut.set_exception(exc)
                    break
                except TimeoutError as exc:
                    # A wedged worker is useless — kill it; but do
                    # NOT rerun the job (doubling a 300s wait).
                    from kind_tpu_sim import metrics

                    self._procs[slot] = None
                    proc.kill()
                    metrics.recovery_log().record(
                        "worker_hang_killed", slot=slot,
                        job=req.get("job"))
                    fut.set_exception(exc)
                    break
                if resp.get("ok"):
                    fut.set_result(resp.get("result"))
                else:
                    fut.set_exception(JobError(
                        resp.get("error", "job failed"),
                        resp.get("traceback", "")))
                break
            self._busy[slot] = False

    # -- lifecycle ----------------------------------------------------

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self.stop_heartbeat()
        for _ in self._threads:
            self._queue.put(_SHUTDOWN)
        for thread in self._threads:
            thread.join(timeout=5)
        for proc in self._procs:
            if proc is not None:
                proc.shutdown()

    def __enter__(self) -> "WorkerPool":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# ---------------------------------------------------------------------
# cold grid (multihost slice driver)


def run_grid(worker_envs: Sequence[Dict[str, str]], target: str,
             timeout: float,
             kwargs_list: Optional[Sequence[dict]] = None,
             max_respawns: int = 0,
             detector=None) -> List:
    """Spawn one COLD protocol worker per env dict, run ``target``
    (a ``module:attr`` callable) in each, and return the results in
    spawn order.

    The multihost slice launcher: each env carries the full
    plugin-style identity (worker id, hostnames, rendezvous port), so
    jax must not load before the job sets it all up — hence cold
    workers. Semantics match the old file-based launcher: a crashed
    worker raises RuntimeError with its stderr tail (killing the
    rest), workers still pending at the deadline raise TimeoutError.

    ``max_respawns`` > 0 turns on the self-healing path: a worker
    that dies before answering is respawned (same identity env, the
    injected CHAOS_FAULT_ENV stripped — a respawn heals) and its job
    resent, up to that many times PER worker; results are identical
    to a fault-free run because each job is a pure function of its
    env + kwargs. Rendezvous launchers keep 0: one dead member wedges
    the whole jax.distributed world, so the recovery unit there is
    the launch attempt (multihost._with_launch_retry), not the
    worker.

    ``detector`` (a kind_tpu_sim.health.FailureDetector) observes
    each worker's reported job time — gang members are identity-bound
    so a straggler cannot be rebalanced mid-grid, but sustained
    suspicion surfaces in the detector for the NEXT launch to act on
    (docs/HEALTH.md)."""
    from kind_tpu_sim import metrics

    def send_job(proc: _WorkerProc, worker: int) -> None:
        proc.send({
            "id": worker, "job": "call",
            "kwargs": {
                "target": target,
                "kwargs": (kwargs_list[worker]
                           if kwargs_list else {}),
            },
        })

    procs: List[_WorkerProc] = []
    with tempfile.TemporaryDirectory() as logdir:
        logs = pathlib.Path(logdir)
        try:
            for worker, extra in enumerate(worker_envs):
                env = _pool_child_env(extra, warm=False)
                procs.append(_WorkerProc(
                    env, stderr_path=logs / f"worker-{worker}.err"))
            deadline = time.monotonic() + timeout
            for worker, proc in enumerate(procs):
                try:
                    send_job(proc, worker)
                except WorkerCrash:
                    raise RuntimeError(
                        f"slice worker {worker} crashed at spawn "
                        f"(rc={proc.proc.poll()}):\n"
                        f"{proc.stderr_tail()}")
            results: List = [None] * len(procs)
            pending = set(range(len(procs)))
            respawns_left = [max_respawns] * len(procs)
            while pending:
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"slice workers {sorted(pending)} still "
                        f"running after {timeout}s")
                for worker in sorted(pending):
                    proc = procs[worker]
                    try:
                        frame = proc.read_frame(
                            min(deadline,
                                time.monotonic() + 0.25))
                    except TimeoutError:
                        continue
                    except WorkerCrash:
                        rc = proc.proc.poll()
                        if respawns_left[worker] <= 0:
                            raise RuntimeError(
                                f"slice worker {worker} crashed "
                                f"(rc={rc}):\n{proc.stderr_tail()}")
                        respawns_left[worker] -= 1
                        proc.kill()
                        env = _pool_child_env(
                            worker_envs[worker], warm=False)
                        env.pop(CHAOS_FAULT_ENV, None)
                        retry_no = max_respawns - respawns_left[worker]
                        fresh = _WorkerProc(
                            env, stderr_path=logs
                            / f"worker-{worker}-r{retry_no}.err")
                        procs[worker] = fresh
                        metrics.recovery_log().record(
                            "grid_worker_respawn", worker=worker,
                            rc=rc, retry=retry_no)
                        log.warning(
                            "grid worker %d died (rc=%s); respawning "
                            "and resending its job (%d/%d)", worker,
                            rc, retry_no, max_respawns)
                        try:
                            send_job(fresh, worker)
                        except WorkerCrash:
                            raise RuntimeError(
                                f"slice worker {worker} crashed at "
                                f"respawn (rc={fresh.proc.poll()}):\n"
                                f"{fresh.stderr_tail()}")
                        continue
                    if frame.get("hello"):
                        continue  # cold hello precedes the result
                    if not frame.get("ok"):
                        raise RuntimeError(
                            f"slice worker {worker} job failed: "
                            f"{frame.get('error')}\n"
                            f"{frame.get('traceback', '')[-1000:]}")
                    if (detector is not None
                            and frame.get("elapsed_s") is not None):
                        detector.observe(
                            f"grid-worker-{worker}",
                            float(frame["elapsed_s"]),
                            now=time.monotonic())
                    results[worker] = frame.get("result")
                    pending.discard(worker)
            return results
        finally:
            for proc in procs:
                proc.kill()


def run_cells(worker_envs: Sequence[Dict[str, str]], target: str,
              cells: Sequence[dict], timeout: float,
              cell_timeout: Optional[float] = None,
              max_respawns: int = 1,
              fault: Optional[tuple] = None,
              detect: bool = False,
              health_cfg=None,
              batch: int = 1):
    """Dynamic grid-cell scheduler over COLD protocol workers: every
    worker pulls the next unclaimed cell, so the grid drains at the
    speed of the survivors even when a worker dies.

    Recovery contract (docs/CHAOS.md): a worker that crashes or hangs
    mid-cell has that cell REQUEUED — picked up by a survivor, or by
    the worker's own respawn when it still has budget (the injected
    CHAOS_FAULT_ENV is stripped on respawn, so a chaos fault is
    transient by construction). A hang is detected by
    ``cell_timeout`` and the wedged worker killed. Results are
    indexed by cell, so a faulted run returns EXACTLY what the
    fault-free run returns — each cell is a pure function of its
    kwargs. A cell whose job RAISES is deterministic and fails the
    whole run (retrying it would just re-raise slower).

    ``fault`` is the DETERMINISTIC chaos lever. Fail-stop kinds
    target a CELL: ("crash"|"hang", cell_index[, seconds]) sends a
    genuine crash/hang job in that cell's place on its first
    dispatch, consumed exactly once. Gray kinds target a WORKER:
    ("straggler"|"flaky", worker_index, stall_seconds) plants a
    "slow@1:S" / "flaky@2:S" CHAOS_FAULT_ENV in that worker's env —
    alive, correct, slow (docs/HEALTH.md).

    ``detect=True`` turns on the gray-failure layer
    (kind_tpu_sim.health, knobs via ``health_cfg`` or the
    KIND_TPU_SIM_HEALTH_* env):

    * each worker is PROBED (a ping bounded by ``probe_timeout_s``)
      before it may pull cells; a probe that misses its deadline
      quarantines the worker, and a respawn (budget permitting)
      replaces and restores it;
    * per-cell service times feed the phi-accrual detector; a worker
      whose samples go suspicious enough to quarantine stops pulling
      cells (rebalanced away) and is respawned when budget remains;
    * once the queue is empty, the slowest tail cell still in flight
      on a suspect worker is SPECULATIVELY re-dispatched to an idle
      worker — first result wins (cells are pure functions, so the
      copies are identical by construction).

    ``batch`` > 1 pulls up to that many cells per protocol round
    trip (one ``call_batch`` job) — the framing/dispatch
    amortization for grids of cheap cells. Results stay
    position-identical to single dispatch (each cell is the same
    pure function of its kwargs); a crashed batch requeues every
    unfinished member. Batching auto-disables under ``fault`` /
    ``detect``: the chaos and gray-failure contracts are specified
    per-request, and changing the request stream would change which
    requests a planted fault hits.

    Returns ``(results, stats)``: results in cell order, stats with
    requeue/respawn/quarantine/speculation counts plus
    ``makespan_s`` (first dispatch -> last completion) — also
    recorded in metrics.recovery_log / metrics.health_board.
    """
    from kind_tpu_sim import metrics

    detector = None
    hcfg = None
    if detect:
        from kind_tpu_sim import health as health_mod

        hcfg = health_cfg or health_mod.DetectorConfig.from_env()
        detector = health_mod.FailureDetector(hcfg)

    gray_fault = (fault if fault is not None
                  and fault[0] in ("straggler", "flaky") else None)
    cell_fault = fault if gray_fault is None else None
    if fault is not None or detect:
        batch = 1
    batch = max(1, int(batch))

    deadline = time.monotonic() + timeout
    cond = threading.Condition()
    all_done = threading.Event()
    todo: List[int] = list(range(len(cells)))
    inflight: set = set()
    dispatch_t: Dict[int, float] = {}
    spec_extra: Dict[int, int] = {}
    fatal: List[BaseException] = []
    results: List = [None] * len(cells)
    ok: List[bool] = [False] * len(cells)
    done_count = [0]
    span = [None, None]  # first dispatch, last completion
    stats = {"workers": len(worker_envs), "requeues": 0,
             "respawns": 0, "faults_injected": 0,
             "probes": 0, "probe_failures": 0,
             "quarantines": 0, "speculative": 0}
    fault_budget = [1 if cell_fault else 0]

    def next_cells() -> Optional[List[int]]:
        with cond:
            while True:
                if fatal or time.monotonic() > deadline:
                    return None
                if todo:
                    picked = todo[:batch]
                    del todo[:len(picked)]
                    now = time.monotonic()
                    for idx in picked:
                        inflight.add(idx)
                        dispatch_t.setdefault(idx, now)
                    if span[0] is None:
                        span[0] = now
                    return picked
                if not inflight:
                    return None
                if detector is not None:
                    idx = _pick_speculative()
                    if idx is not None:
                        return [idx]
                cond.wait(0.05)

    def _pick_speculative() -> Optional[int]:
        # caller holds cond. The slowest (oldest) tail cell still in
        # flight, once past spec_age_ratio x the expected service
        # time, earns ONE speculative copy — first result wins.
        expected = detector.expected_s()
        if expected is None:
            return None
        now = time.monotonic()
        for idx in sorted(inflight,
                          key=lambda i: dispatch_t.get(i, now)):
            if ok[idx] or spec_extra.get(idx, 0) >= 1:
                continue
            age = now - dispatch_t.get(idx, now)
            if age > hcfg.spec_age_ratio * expected:
                spec_extra[idx] = spec_extra.get(idx, 0) + 1
                stats["speculative"] += 1
                metrics.health_board().incr("speculative_redispatch")
                metrics.recovery_log().record(
                    "cell_speculated", cell=idx,
                    age_s=round(age, 3))
                return idx
        return None

    def finish(idx: int, success: bool) -> None:
        with cond:
            inflight.discard(idx)
            if success:
                if not ok[idx]:
                    ok[idx] = True
                    done_count[0] += 1
                    span[1] = time.monotonic()
                    if done_count[0] == len(cells):
                        all_done.set()
            elif not ok[idx] and idx not in todo:
                todo.insert(0, idx)
                stats["requeues"] += 1
            cond.notify_all()

    def probe(proc: "_WorkerProc", comp: str) -> bool:
        """Bounded ping before the worker may pull cells. RTTs are
        NOT fed to the EWMA baseline (pings and cells are different
        distributions); the probe verdict is binary."""
        stats["probes"] += 1
        try:
            proc.request({"id": -1, "job": "ping"},
                         time.monotonic() + hcfg.probe_timeout_s)
        except (WorkerCrash, TimeoutError):
            stats["probe_failures"] += 1
            if detector.record_probe(
                    comp, ok=False,
                    now=time.monotonic()) == "quarantined":
                stats["quarantines"] += 1
            return False
        detector.record_probe(comp, ok=True, now=time.monotonic())
        return True

    def respawn(env: Dict[str, str], proc: "_WorkerProc",
                worker: int) -> "_WorkerProc":
        proc.kill()
        with cond:
            stats["respawns"] += 1
        env.pop(CHAOS_FAULT_ENV, None)
        fresh = _WorkerProc(env)
        metrics.recovery_log().record(
            "cell_worker_respawn", worker=worker, pid=fresh.pid)
        return fresh

    def _drive_batch(proc: "_WorkerProc", worker: int,
                     idxs: List[int]) -> str:
        """One batched dispatch (fault/detect off by construction).
        Returns "ok", "crash" (requeued — caller may respawn), or
        "stop" (fatal job error / cancellation)."""
        cell_deadline = deadline
        if cell_timeout is not None:
            cell_deadline = min(
                deadline,
                time.monotonic() + cell_timeout * len(idxs))
        req = {"id": idxs[0], "job": "call_batch",
               "kwargs": {"target": target,
                          "kwargs_list": [dict(cells[i])
                                          for i in idxs]}}
        try:
            resp = proc.request(req, cell_deadline,
                                cancel=all_done)
        except WorkerCancelled:
            proc.kill()
            return "stop"
        except (WorkerCrash, TimeoutError) as exc:
            for idx in idxs:
                finish(idx, False)
            metrics.recovery_log().record(
                "cell_requeued", cell=idxs[0], worker=worker,
                cause=type(exc).__name__, batch=len(idxs))
            proc.kill()
            return "crash"
        if not resp.get("ok"):
            with cond:
                fatal.append(RuntimeError(
                    f"cells {idxs} failed on worker {worker}: "
                    f"{resp.get('error')}\n"
                    f"{resp.get('traceback', '')[-1000:]}"))
                cond.notify_all()
            return "stop"
        for pos, idx in enumerate(idxs):
            results[idx] = resp["result"][pos]
            finish(idx, True)
        return "ok"

    def drive(worker: int) -> None:
        env = _pool_child_env(worker_envs[worker], warm=False)
        if (gray_fault is not None
                and gray_fault[1] % len(worker_envs) == worker):
            stall = float(gray_fault[2] if len(gray_fault) > 2
                          else 1.0)
            env[CHAOS_FAULT_ENV] = (
                f"slow@1:{stall}" if gray_fault[0] == "straggler"
                else f"flaky@2:{stall}")
            with cond:
                stats["faults_injected"] += 1
            metrics.recovery_log().record(
                "fault_injected", kind=gray_fault[0], worker=worker)
        proc = _WorkerProc(env)
        comp = f"worker-{worker}"
        respawns_left = max_respawns
        try:
            if detector is not None:
                healthy = probe(proc, comp)
                while not healthy:
                    if respawns_left <= 0:
                        return  # quarantined for good; peers drain
                    respawns_left -= 1
                    proc = respawn(dict(env), proc, worker)
                    healthy = probe(proc, comp)
                    if healthy:
                        detector.restore(comp, time.monotonic(),
                                         reason="respawned")
            while True:
                idxs = next_cells()
                if idxs is None:
                    return
                if len(idxs) > 1:
                    status = _drive_batch(proc, worker, idxs)
                    if status == "ok":
                        continue
                    if status == "crash":
                        if respawns_left <= 0:
                            return  # survivors drain the requeue
                        respawns_left -= 1
                        proc = respawn(dict(env), proc, worker)
                        continue
                    return
                idx = idxs[0]
                cell_deadline = deadline
                if cell_timeout is not None:
                    cell_deadline = min(
                        deadline, time.monotonic() + cell_timeout)
                req = {"id": idx, "job": "call",
                       "kwargs": {"target": target,
                                  "kwargs": dict(cells[idx])}}
                if cell_fault is not None and idx == cell_fault[1]:
                    with cond:
                        inject = fault_budget[0] > 0
                        if inject:
                            fault_budget[0] -= 1
                            stats["faults_injected"] += 1
                    if inject:
                        if cell_fault[0] == "crash":
                            req = {"id": idx, "job": "crash",
                                   "kwargs": {}}
                        elif cell_fault[0] == "hang":
                            req = {"id": idx, "job": "hang",
                                   "kwargs": {"seconds": float(
                                       cell_fault[2]
                                       if len(cell_fault) > 2
                                       else 3600.0)}}
                        metrics.recovery_log().record(
                            "fault_injected", kind=cell_fault[0],
                            cell=idx, worker=worker)
                t0 = time.monotonic()
                try:
                    resp = proc.request(req, cell_deadline,
                                        cancel=all_done)
                except WorkerCancelled:
                    # the grid finished through a speculative copy
                    # while this worker still chewed on its cell
                    proc.kill()
                    return
                except (WorkerCrash, TimeoutError) as exc:
                    finish(idx, False)
                    metrics.recovery_log().record(
                        "cell_requeued", cell=idx, worker=worker,
                        cause=type(exc).__name__)
                    proc.kill()
                    if respawns_left <= 0:
                        return  # survivors drain the requeued cell
                    respawns_left -= 1
                    proc = respawn(dict(env), proc, worker)
                    continue
                if not resp.get("ok"):
                    with cond:
                        fatal.append(RuntimeError(
                            f"cell {idx} failed on worker {worker}: "
                            f"{resp.get('error')}\n"
                            f"{resp.get('traceback', '')[-1000:]}"))
                        cond.notify_all()
                    return
                results[idx] = resp.get("result")
                finish(idx, True)
                if detector is not None:
                    transition = detector.observe(
                        comp, time.monotonic() - t0,
                        now=time.monotonic())
                    if transition == "quarantined":
                        with cond:
                            stats["quarantines"] += 1
                        proc.kill()
                        if respawns_left <= 0:
                            return  # rebalanced away for good
                        respawns_left -= 1
                        proc = respawn(dict(env), proc, worker)
                        if not probe(proc, comp):
                            return
                        detector.restore(comp, time.monotonic(),
                                         reason="respawned")
        finally:
            proc.kill()
            with cond:
                cond.notify_all()

    threads = [threading.Thread(target=drive, args=(w,),
                                name=f"tpu-sim-cells-{w}",
                                daemon=True)
               for w in range(len(worker_envs))]
    for thread in threads:
        thread.start()
    for thread in threads:
        thread.join(timeout=max(0.0, deadline - time.monotonic())
                    + 10.0)
    if span[0] is not None and span[1] is not None:
        stats["makespan_s"] = round(span[1] - span[0], 6)
    if detector is not None:
        # transitions only (no wall times): the byte-stable shape
        # chaos scenario reports embed (docs/HEALTH.md)
        stats["detection"] = [
            {"component": e["component"],
             "transition": e["transition"]}
            for e in detector.events]
    if fatal:
        raise fatal[0]
    missing = [i for i, done in enumerate(ok) if not done]
    if missing:
        raise TimeoutError(
            f"cells {missing} unfinished after {timeout}s "
            f"(requeues={stats['requeues']}, "
            f"respawns={stats['respawns']})")
    return results, stats


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--serve" in argv:
        return _serve()
    print("usage: python -m kind_tpu_sim.utils.worker_pool --serve",
          file=sys.stderr)
    return 2


if __name__ == "__main__":
    sys.exit(main())
