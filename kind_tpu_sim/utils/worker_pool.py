"""Persistent JAX worker pool — the simulator's warm-path runtime.

The bench/CLI cold path used to pay `import jax` + backend init +
XLA compile in a fresh subprocess for EVERY JAX-touching phase
(r05: 99.7% of the stack-ready headline was one cold subprocess).
This module makes that cost once-per-session: long-lived CPU-backend
Python workers, preforked once per bench/CLI process, that callers
submit JAX jobs to over a small length-prefixed JSON protocol on the
worker's stdin/stdout pipes.

Protocol (both directions): 4-byte big-endian length, then a UTF-8
JSON object. The worker's FIRST frame is a hello carrying its pid and
(when preforked warm) the measured warm-up seconds; every later frame
answers exactly one request, in order:

    request:  {"id": 3, "job": "psum_smoke", "kwargs": {...}}
    response: {"id": 3, "ok": true, "result": {...}, "elapsed_s": 0.04}

The worker rebinds its real stdout to stderr before serving, so stray
prints (jax warnings, absl logs) can never corrupt the framing.

Failure contract: a job that raises inside the worker returns
``ok: false`` and surfaces as :class:`JobError` (no respawn — the
worker is still healthy). A worker that DIES mid-job (EOF on the
pipe) is respawned and the job retried once; a second death raises
:class:`WorkerCrash` with the worker's stderr tail. A job deadline
kills the (possibly wedged) worker and raises ``TimeoutError``
without retrying — retrying a timeout would double the wait.

Two spawn temperatures:

* warm (default) — the worker imports jax and initializes the
  backend immediately at spawn; the hello reports ``warm_s``.
  bench.py overlaps this warm-up with the orchestrator/plugin
  bring-up phases.
* cold (``warm=False`` / :func:`run_grid`) — a bare protocol loop
  with nothing imported; used by the multihost slice driver, whose
  workers must set per-process identity env before jax ever loads.

Workers inherit :func:`kind_tpu_sim.utils.shell.cpu_subprocess_env`,
so the persistent XLA compilation cache (``.cache/jax``) is wired in
for every pooled job too.
"""

from __future__ import annotations

import json
import logging
import os
import pathlib
import queue
import selectors
import struct
import subprocess
import sys
import tempfile
import threading
import time
from concurrent.futures import Future
from typing import Dict, List, Optional, Sequence

log = logging.getLogger("kind-tpu-sim")

WARM_ENV = "KIND_TPU_SIM_POOL_WARM"

# A frame bigger than this is protocol corruption, not data.
MAX_FRAME_BYTES = 64 * 1024 * 1024

REPO_ROOT = pathlib.Path(__file__).resolve().parents[2]


class JobError(RuntimeError):
    """The job raised inside the worker (worker itself is healthy)."""

    def __init__(self, message: str, remote_traceback: str = ""):
        super().__init__(message)
        self.remote_traceback = remote_traceback


class WorkerCrash(RuntimeError):
    """The worker process died before answering."""


# ---------------------------------------------------------------------
# framing


def write_frame(stream, obj) -> None:
    payload = json.dumps(obj).encode("utf-8")
    stream.write(struct.pack(">I", len(payload)) + payload)
    stream.flush()


def read_frame(stream):
    """Blocking frame read from a binary stream; None on clean EOF."""
    header = stream.read(4)
    if not header:
        return None
    if len(header) < 4:
        raise EOFError("truncated frame header")
    (length,) = struct.unpack(">I", header)
    if length > MAX_FRAME_BYTES:
        raise EOFError(f"implausible frame length {length}")
    payload = b""
    while len(payload) < length:
        chunk = stream.read(length - len(payload))
        if not chunk:
            raise EOFError("truncated frame payload")
        payload += chunk
    return json.loads(payload.decode("utf-8"))


# ---------------------------------------------------------------------
# worker side


def _warmup() -> dict:
    """Import jax and initialize the backend (the once-per-session
    cost the pool exists to amortize)."""
    import jax

    platforms = os.environ.get("JAX_PLATFORMS")
    if platforms:
        # Pin the config even when a site plugin (axon) registers
        # itself regardless of the env var — same defense as
        # tests/conftest.py.
        jax.config.update("jax_platforms", platforms)
    return {
        "devices": jax.device_count(),
        "backend": jax.default_backend(),
        "jax_version": jax.__version__,
    }


def _job_ping() -> dict:
    return {"pid": os.getpid()}


def _job_psum_smoke(topology: str = "2x4",
                    expect_devices: Optional[int] = None) -> dict:
    """The BASELINE acceptance gate: all advertised fake chips visible
    and a psum verified over them."""
    info = _warmup()
    if expect_devices is not None and info["devices"] != expect_devices:
        raise RuntimeError(
            f"{info['devices']} devices visible, expected "
            f"{expect_devices}")
    from kind_tpu_sim import topology as topo
    from kind_tpu_sim.parallel import collectives, mesh

    report = collectives.psum_smoke(
        mesh.slice_mesh(topo.make_slice(topology=topology)))
    if not report.get("ok"):
        raise RuntimeError(f"psum smoke failed: {report}")
    report["worker_pid"] = os.getpid()
    return report


def _job_collectives_suite(topology: str = "2x4") -> dict:
    info = _warmup()
    from kind_tpu_sim import topology as topo
    from kind_tpu_sim.parallel import collectives, mesh

    report = collectives.run_all(
        mesh.slice_mesh(topo.make_slice(topology=topology)))
    report["devices"] = info["devices"]
    report["worker_pid"] = os.getpid()
    return report


def _job_call(target: str, kwargs: Optional[dict] = None):
    """Generic job: ``module.path:attr`` resolved and called in the
    (warm) worker — how bench.py runs the ring bench and the multihost
    grid runs its per-host report without a bespoke job each."""
    import importlib

    mod_name, _, attr_path = target.partition(":")
    if not attr_path:
        raise ValueError(f"target {target!r} must be 'module:attr'")
    obj = importlib.import_module(mod_name)
    for attr in attr_path.split("."):
        obj = getattr(obj, attr)
    return obj(**(kwargs or {}))


def _job_psum_cache_probe(topology: str = "2x4") -> dict:
    """psum smoke + XLA persistent-cache hit/miss counters.

    The diagnostic behind the warm-path story: a first-ever run
    reports misses (the cache is being populated), a later worker on
    the same cache dir reports hits (the compile was skipped). Must
    run before any other compile in this worker so the counters
    belong to the smoke alone."""
    import jax

    counts = {"cache_hits": 0, "cache_misses": 0}

    def listener(event: str, **kw) -> None:
        for key in counts:
            if event.endswith(key):
                counts[key] += 1

    jax.monitoring.register_event_listener(listener)
    report = _job_psum_smoke(topology=topology)
    report.update(counts)
    report["cache_enabled"] = bool(
        os.environ.get("JAX_COMPILATION_CACHE_DIR"))
    return report


def _job_crash(code: int = 13) -> None:
    """Die without answering — the chaos hook the crash-recovery
    tests (and `chaos`-minded users) exercise the respawn path with."""
    os._exit(code)


JOBS = {
    "ping": _job_ping,
    "warmup": _warmup,
    "psum_smoke": _job_psum_smoke,
    "psum_cache_probe": _job_psum_cache_probe,
    "collectives_suite": _job_collectives_suite,
    "call": _job_call,
    "crash": _job_crash,
}


def _serve() -> int:
    """Worker main loop: hello, then answer requests until EOF."""
    import traceback

    # Bind the protocol to the ORIGINAL stdout, then point fd 1 at
    # stderr: later stray writes (warnings, absl) land in the log
    # channel instead of corrupting frames.
    proto_fd = os.dup(1)
    os.dup2(2, 1)
    out = os.fdopen(proto_fd, "wb")
    inp = sys.stdin.buffer

    hello = {"hello": True, "pid": os.getpid()}
    if os.environ.get(WARM_ENV) == "1":
        t0 = time.monotonic()
        try:
            hello.update(_warmup())
            hello["warm_s"] = round(time.monotonic() - t0, 3)
        except Exception as exc:  # surfaced to the parent, not fatal
            hello["warm_error"] = f"{type(exc).__name__}: {exc}"[:500]
    write_frame(out, hello)

    while True:
        try:
            req = read_frame(inp)
        except EOFError:
            return 1
        if req is None or req.get("op") == "shutdown":
            return 0
        resp = {"id": req.get("id")}
        t0 = time.monotonic()
        try:
            job = JOBS[req["job"]]
            resp["result"] = job(**(req.get("kwargs") or {}))
            resp["ok"] = True
        except Exception as exc:
            resp["ok"] = False
            resp["error"] = f"{type(exc).__name__}: {exc}"[:2000]
            resp["traceback"] = traceback.format_exc()[-2000:]
        resp["elapsed_s"] = round(time.monotonic() - t0, 6)
        write_frame(out, resp)


# ---------------------------------------------------------------------
# parent side


def _pool_child_env(extra_env: Optional[Dict[str, str]] = None,
                    warm: bool = True) -> Dict[str, str]:
    from kind_tpu_sim.utils.shell import cpu_subprocess_env

    env = cpu_subprocess_env()
    env.update(extra_env or {})
    env["PYTHONPATH"] = (str(REPO_ROOT) + os.pathsep
                         + env.get("PYTHONPATH", ""))
    env[WARM_ENV] = "1" if warm else "0"
    return env


def simulated_slice_env(chips: int = 8) -> Dict[str, str]:
    """Env for a worker simulating one host of a slice: CPU backend
    exposing ``chips`` virtual devices (the jax-tpu-pod trick)."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        flags = (flags
                 + f" --xla_force_host_platform_device_count={chips}"
                 ).strip()
    return {"JAX_PLATFORMS": "cpu", "XLA_FLAGS": flags}


class _WorkerProc:
    """One protocol worker process + its read buffer and stderr log."""

    def __init__(self, env: Dict[str, str],
                 stderr_path: Optional[pathlib.Path] = None):
        self._buf = b""
        self.hello: Optional[dict] = None
        self.spawned_at = time.monotonic()
        if stderr_path is None:
            fd, name = tempfile.mkstemp(prefix="tpu-sim-worker-",
                                        suffix=".err")
            self.stderr_path = pathlib.Path(name)
            self._stderr_file = os.fdopen(fd, "wb")
            self._own_stderr = True
        else:
            self.stderr_path = stderr_path
            self._stderr_file = open(stderr_path, "wb")
            self._own_stderr = False
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "kind_tpu_sim.utils.worker_pool",
             "--serve"],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=self._stderr_file, env=env,
        )

    @property
    def pid(self) -> int:
        return self.proc.pid

    def alive(self) -> bool:
        return self.proc.poll() is None

    def stderr_tail(self, n: int = 2000) -> str:
        try:
            self._stderr_file.flush()
            return self.stderr_path.read_text(errors="replace")[-n:]
        except OSError:
            return ""

    def read_frame(self, deadline: float):
        """One frame from the worker's stdout, or raise: WorkerCrash
        on EOF/death, TimeoutError past ``deadline``."""
        fd = self.proc.stdout.fileno()
        sel = selectors.DefaultSelector()
        sel.register(self.proc.stdout, selectors.EVENT_READ)
        try:
            while True:
                frame, self._buf = _try_parse(self._buf)
                if frame is not None:
                    return frame
                remain = deadline - time.monotonic()
                if remain <= 0:
                    raise TimeoutError(
                        f"worker {self.pid} gave no answer in time")
                if not sel.select(timeout=min(remain, 1.0)):
                    if not self.alive():
                        raise WorkerCrash(
                            f"worker {self.pid} exited "
                            f"(rc={self.proc.returncode}): "
                            f"{self.stderr_tail()}")
                    continue
                data = os.read(fd, 65536)
                if not data:
                    raise WorkerCrash(
                        f"worker {self.pid} closed its pipe "
                        f"(rc={self.proc.poll()}): "
                        f"{self.stderr_tail()}")
                self._buf += data
        finally:
            sel.close()

    def ensure_ready(self, deadline: float) -> dict:
        if self.hello is None:
            self.hello = self.read_frame(deadline)
        return self.hello

    def request(self, req: dict, deadline: float) -> dict:
        self.ensure_ready(deadline)
        try:
            write_frame(self.proc.stdin, req)
        except (BrokenPipeError, OSError) as exc:
            raise WorkerCrash(
                f"worker {self.pid} pipe closed: {exc}; "
                f"{self.stderr_tail()}") from exc
        return self.read_frame(deadline)

    def kill(self) -> None:
        if self.alive():
            self.proc.kill()
        try:
            self.proc.wait(timeout=10)
        except subprocess.TimeoutExpired:  # pragma: no cover
            pass
        self.close_files()

    def shutdown(self, grace_s: float = 2.0) -> None:
        try:
            if self.alive():
                write_frame(self.proc.stdin, {"op": "shutdown"})
                self.proc.stdin.close()
                self.proc.wait(timeout=grace_s)
        except (OSError, subprocess.TimeoutExpired):
            pass
        self.kill()

    def close_files(self) -> None:
        try:
            self._stderr_file.close()
        except OSError:  # pragma: no cover
            pass
        if self._own_stderr:
            try:
                self.stderr_path.unlink()
            except OSError:  # pragma: no cover
                pass


def _try_parse(buf: bytes):
    """(frame, rest) if ``buf`` holds a complete frame, else
    (None, buf)."""
    if len(buf) < 4:
        return None, buf
    (length,) = struct.unpack(">I", buf[:4])
    if length > MAX_FRAME_BYTES:
        raise WorkerCrash(f"implausible frame length {length}")
    if len(buf) < 4 + length:
        return None, buf
    return json.loads(buf[4:4 + length].decode("utf-8")), buf[4 + length:]


_SHUTDOWN = object()


class WorkerPool:
    """Preforked protocol workers + a submit queue.

    ``submit_async`` returns a :class:`concurrent.futures.Future`;
    one dispatcher thread per worker drains the shared queue, so a
    pool of size N runs N jobs concurrently and a caller never blocks
    on spawn/warm-up unless it asks for a result.
    """

    def __init__(self, size: int = 1, warm: bool = True,
                 extra_env: Optional[Dict[str, str]] = None,
                 job_timeout: float = 300.0):
        self._env = _pool_child_env(extra_env, warm=warm)
        self._timeout = job_timeout
        self._queue: "queue.Queue" = queue.Queue()
        self._lock = threading.Lock()
        self._next_id = 0
        self._closed = False
        self.respawns = 0
        self._procs: List[Optional[_WorkerProc]] = []
        self._threads: List[threading.Thread] = []
        for slot in range(size):
            self._procs.append(_WorkerProc(self._env))
            thread = threading.Thread(
                target=self._dispatch, args=(slot,),
                name=f"tpu-sim-pool-{slot}", daemon=True)
            thread.start()
            self._threads.append(thread)

    # -- submission ---------------------------------------------------

    def submit_async(self, job: str, *, timeout: Optional[float] = None,
                     **kwargs) -> Future:
        if self._closed:
            raise RuntimeError("pool is closed")
        with self._lock:
            self._next_id += 1
            req_id = self._next_id
        req = {"id": req_id, "job": job, "kwargs": kwargs}
        fut: Future = Future()
        self._queue.put((fut, req, timeout or self._timeout))
        return fut

    def submit(self, job: str, *, timeout: Optional[float] = None,
               **kwargs):
        return self.submit_async(job, timeout=timeout,
                                 **kwargs).result()

    # -- introspection ------------------------------------------------

    def worker_pids(self) -> List[int]:
        return [p.pid for p in self._procs if p is not None]

    def bringup(self, timeout: float = 120.0) -> dict:
        """A ready worker's hello: pid, and for warm pools the
        measured ``warm_s`` (jax import + backend init) and device
        count."""
        info = dict(self.submit("ping", timeout=timeout))
        for proc in self._procs:
            if proc is not None and proc.hello:
                info.update(proc.hello)
                break
        return info

    # -- dispatch -----------------------------------------------------

    def _respawn(self, slot: int) -> _WorkerProc:
        old = self._procs[slot]
        if old is not None:
            old.kill()
        self.respawns += 1
        proc = _WorkerProc(self._env)
        self._procs[slot] = proc
        return proc

    def _dispatch(self, slot: int) -> None:
        while True:
            item = self._queue.get()
            if item is _SHUTDOWN:
                return
            fut, req, timeout = item
            if not fut.set_running_or_notify_cancel():
                continue
            attempts_left = 1  # one respawn+retry per job
            while True:
                proc = self._procs[slot]
                if proc is None or not proc.alive():
                    proc = self._respawn(slot)
                deadline = time.monotonic() + timeout
                try:
                    resp = proc.request(req, deadline)
                except WorkerCrash as exc:
                    self._procs[slot] = None
                    proc.kill()
                    if attempts_left > 0:
                        attempts_left -= 1
                        log.warning(
                            "pool worker died (%s); respawning and "
                            "retrying job %s once", exc, req["job"])
                        continue
                    fut.set_exception(exc)
                    break
                except TimeoutError as exc:
                    # A wedged worker is useless — kill it; but do
                    # NOT rerun the job (doubling a 300s wait).
                    self._procs[slot] = None
                    proc.kill()
                    fut.set_exception(exc)
                    break
                if resp.get("ok"):
                    fut.set_result(resp.get("result"))
                else:
                    fut.set_exception(JobError(
                        resp.get("error", "job failed"),
                        resp.get("traceback", "")))
                break

    # -- lifecycle ----------------------------------------------------

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        for _ in self._threads:
            self._queue.put(_SHUTDOWN)
        for thread in self._threads:
            thread.join(timeout=5)
        for proc in self._procs:
            if proc is not None:
                proc.shutdown()

    def __enter__(self) -> "WorkerPool":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# ---------------------------------------------------------------------
# cold grid (multihost slice driver)


def run_grid(worker_envs: Sequence[Dict[str, str]], target: str,
             timeout: float,
             kwargs_list: Optional[Sequence[dict]] = None) -> List:
    """Spawn one COLD protocol worker per env dict, run ``target``
    (a ``module:attr`` callable) in each, and return the results in
    spawn order.

    The multihost slice launcher: each env carries the full
    plugin-style identity (worker id, hostnames, rendezvous port), so
    jax must not load before the job sets it all up — hence cold
    workers. Semantics match the old file-based launcher: a crashed
    worker raises RuntimeError with its stderr tail (killing the
    rest), workers still pending at the deadline raise TimeoutError.
    """
    procs: List[_WorkerProc] = []
    with tempfile.TemporaryDirectory() as logdir:
        logs = pathlib.Path(logdir)
        try:
            for worker, extra in enumerate(worker_envs):
                env = _pool_child_env(extra, warm=False)
                procs.append(_WorkerProc(
                    env, stderr_path=logs / f"worker-{worker}.err"))
            deadline = time.monotonic() + timeout
            for worker, proc in enumerate(procs):
                try:
                    write_frame(proc.proc.stdin, {
                        "id": worker, "job": "call",
                        "kwargs": {
                            "target": target,
                            "kwargs": (kwargs_list[worker]
                                       if kwargs_list else {}),
                        },
                    })
                except (BrokenPipeError, OSError):
                    raise RuntimeError(
                        f"slice worker {worker} crashed at spawn "
                        f"(rc={proc.proc.poll()}):\n"
                        f"{proc.stderr_tail()}")
            results: List = [None] * len(procs)
            pending = set(range(len(procs)))
            while pending:
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"slice workers {sorted(pending)} still "
                        f"running after {timeout}s")
                for worker in sorted(pending):
                    proc = procs[worker]
                    try:
                        frame = proc.read_frame(
                            min(deadline,
                                time.monotonic() + 0.25))
                    except TimeoutError:
                        continue
                    except WorkerCrash:
                        rc = proc.proc.poll()
                        raise RuntimeError(
                            f"slice worker {worker} crashed "
                            f"(rc={rc}):\n{proc.stderr_tail()}")
                    if frame.get("hello"):
                        continue  # cold hello precedes the result
                    if not frame.get("ok"):
                        raise RuntimeError(
                            f"slice worker {worker} job failed: "
                            f"{frame.get('error')}\n"
                            f"{frame.get('traceback', '')[-1000:]}")
                    results[worker] = frame.get("result")
                    pending.discard(worker)
            return results
        finally:
            for proc in procs:
                proc.kill()


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--serve" in argv:
        return _serve()
    print("usage: python -m kind_tpu_sim.utils.worker_pool --serve",
          file=sys.stderr)
    return 2


if __name__ == "__main__":
    sys.exit(main())
