"""Shared utilities: subprocess execution layer and tiny YAML emission."""

from kind_tpu_sim.utils.shell import (  # noqa: F401
    CommandError,
    ExecResult,
    Executor,
    FakeExecutor,
    SystemExecutor,
)
