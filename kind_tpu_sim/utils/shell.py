"""Subprocess execution layer for the orchestrator.

The reference shells out directly (``cr()`` at kind-gpu-sim.sh:64-66),
which makes it untestable without docker.  Here every external command
goes through an :class:`Executor`, so unit tests swap in
:class:`FakeExecutor` and assert on the exact command stream — the test
strategy upgrade called out in SURVEY.md §4.
"""

from __future__ import annotations

import dataclasses
import logging
import random
import subprocess
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from kind_tpu_sim.analysis import knobs

log = logging.getLogger("kind-tpu-sim")

# Env-var prefixes of TPU-tunnel sitecustomize hooks (axon): when
# present they register themselves in EVERY new interpreter, taxing
# startup ~0.6-1.7s. CPU-only Python subprocesses strip them.
TUNNEL_ENV_PREFIXES = ("_AXON", "PALLAS_AXON")

# Warm-path knobs (docs/PERFORMANCE.md, docs/KNOBS.md): where the XLA
# persistent compilation cache lives, and the off switch.
CACHE_DIR_ENV = knobs.CACHE_DIR
NO_CACHE_ENV = knobs.NO_COMPILATION_CACHE


def compilation_cache_dir():
    """The repo-local XLA compilation-cache directory (a pathlib.Path),
    or None when caching is disabled via NO_CACHE_ENV. Override the
    location with CACHE_DIR_ENV; default is `<repo>/.cache/jax`
    (gitignored) so psum/ring/transformer compiles amortize across
    bench and CLI invocations on the same host."""
    import pathlib

    if knobs.get(NO_CACHE_ENV):
        return None
    override = knobs.get(CACHE_DIR_ENV)
    if override:
        return pathlib.Path(override)
    repo = pathlib.Path(__file__).resolve().parents[2]
    return repo / ".cache" / "jax"


def compilation_cache_env() -> Dict[str, str]:
    """Env vars that point a JAX child at the persistent compilation
    cache. Empty when caching is disabled or the dir is uncreatable
    (read-only checkout): a child must never fail bring-up over a
    cache it can live without."""
    cache = compilation_cache_dir()
    if cache is None:
        return {}
    try:
        cache.mkdir(parents=True, exist_ok=True)
    except OSError:
        return {}
    return {
        "JAX_COMPILATION_CACHE_DIR": str(cache),
        # The simulator's hot programs (psum smoke, collectives)
        # compile in well under jax's 1s default threshold — cache
        # everything.
        "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS": "0",
        "JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES": "0",
    }


def cpu_subprocess_env(base: Optional[Dict[str, str]] = None
                       ) -> Dict[str, str]:
    """Copy of the environment for a CPU-only Python child, with
    TPU-tunnel startup hooks stripped (see TUNNEL_ENV_PREFIXES) and
    the persistent XLA compilation cache wired in (setdefault, so an
    explicit caller/env choice wins)."""
    import os

    env = dict(os.environ if base is None else base)
    for key in list(env):
        if key.startswith(TUNNEL_ENV_PREFIXES):
            del env[key]
    for key, value in compilation_cache_env().items():
        env.setdefault(key, value)
    return env


@dataclasses.dataclass
class ExecResult:
    returncode: int
    stdout: str = ""
    stderr: str = ""

    @property
    def ok(self) -> bool:
        return self.returncode == 0


class CommandError(RuntimeError):
    def __init__(self, argv: Sequence[str], result: ExecResult,
                 attempts: int = 1):
        self.argv = list(argv)
        self.result = result
        self.attempts = attempts
        tried = f" after {attempts} attempts" if attempts > 1 else ""
        super().__init__(
            f"command failed ({result.returncode}){tried}: "
            f"{' '.join(argv)}\n{result.stderr.strip()}"
        )


# ---------------------------------------------------------------------
# classified retry (docs/CHAOS.md "Retry policy")
#
# Real clusters fail transiently — apiserver blips, etcd leader
# changes, container runtime socket hiccups — and the orchestrator
# used to abort a whole create/bench on the first one. Every kubectl/
# runtime command now routes through run_with_retry: TRANSIENT
# failures back off (exponential + jitter) and retry; FATAL ones
# (typos, missing objects, RBAC) surface immediately — retrying a
# deterministic error just doubles the latency to the real message.

# Error-text fragments that mark a failure as worth retrying.
TRANSIENT_PATTERNS = (
    "connection refused", "connection reset", "connection timed out",
    "timed out", "i/o timeout", "context deadline exceeded",
    "tls handshake", "temporarily unavailable",
    "service unavailable", "too many requests", "try again",
    "etcdserver: request timed out", "etcdserver: leader changed",
    "the object has been modified", "no route to host", "dial tcp",
    "internal error occurred", "transport is closing",
    "unexpected eof", "broken pipe",
)

# Deterministic failures — checked FIRST so "...invalid... timed
# out"-ish composites don't retry a request that can never succeed.
FATAL_PATTERNS = (
    "not found", "notfound", "no such", "unknown command",
    "unknown flag", "unrecognized", "invalid", "forbidden",
    "unauthorized", "already exists",
    "executable file not found",
)

# Exit codes of timeout-style kills (`timeout` uses 124; SIGKILL'd
# children report 137) — transient by definition.
TRANSIENT_RETURNCODES = (124, 137)

MAX_RETRIES_ENV = knobs.MAX_RETRIES
RETRY_BASE_MS_ENV = knobs.RETRY_BASE_MS
CMD_TIMEOUT_ENV = knobs.CMD_TIMEOUT_S


def classify_failure(result: ExecResult) -> str:
    """'transient' or 'fatal' for a failed ExecResult.

    Fatal patterns win over transient ones; an unrecognized error is
    FATAL (never retry what we can't name — a wrong default here
    turns every real bug into N× the wait)."""
    text = (result.stderr + "\n" + result.stdout).lower()
    if any(pat in text for pat in FATAL_PATTERNS):
        return "fatal"
    if result.returncode in TRANSIENT_RETURNCODES:
        return "transient"
    if any(pat in text for pat in TRANSIENT_PATTERNS):
        return "transient"
    return "fatal"


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Backoff knobs for transient command failures.

    ``seed`` pins the jitter (chaos tests assert exact schedules);
    None draws entropy. ``deadline_s`` is the PER-COMMAND wall cap
    (None = no cap) — a wedged kubectl is killed and classified
    transient instead of hanging the whole pipeline."""

    max_retries: int = 3
    base_ms: float = 50.0
    max_ms: float = 2000.0
    deadline_s: Optional[float] = None
    seed: Optional[int] = None

    @classmethod
    def from_env(cls, environ: Optional[Dict[str, str]] = None
                 ) -> "RetryPolicy":
        # CHAOS_SEED deliberately resolves to None (not the knob's 0
        # default) when unset: an unseeded policy draws fresh jitter,
        # while any explicit seed pins the backoff schedule.
        raw_seed = knobs.get_raw(knobs.CHAOS_SEED, environ)
        try:
            seed = int(raw_seed) if raw_seed is not None else None
        except ValueError:
            seed = None
        return cls(
            max_retries=knobs.get(MAX_RETRIES_ENV, environ),
            base_ms=knobs.get(RETRY_BASE_MS_ENV, environ),
            deadline_s=knobs.get(CMD_TIMEOUT_ENV, environ),
            seed=seed,
        )

    def backoff_s(self, attempt: int, rng: random.Random) -> float:
        """Delay before retry ``attempt`` (0-based): exponential
        doubling from base_ms, full jitter on top, capped at
        max_ms."""
        base = min(self.base_ms * (2 ** attempt), self.max_ms)
        return (base + rng.uniform(0.0, self.base_ms)) / 1000.0


def run_with_retry(
    executor: "Executor",
    argv: Sequence[str],
    *,
    policy: Optional[RetryPolicy] = None,
    input_text: Optional[str] = None,
    check: bool = True,
    env: Optional[Dict[str, str]] = None,
) -> ExecResult:
    """Run ``argv`` through ``executor`` with the classified retry
    policy: transient failures back off and retry (up to
    ``max_retries``); fatal ones (and exhaustion) surface as
    CommandError carrying the attempt count. Every retry is recorded
    in metrics.recovery_log() so recovery is observable."""
    from kind_tpu_sim import metrics

    policy = policy or RetryPolicy.from_env()
    rng = random.Random(policy.seed)
    attempts = max(0, policy.max_retries) + 1
    result = ExecResult(1, "", "retry loop did not run")
    for attempt in range(attempts):
        result = executor.run(
            argv, input_text=input_text, check=False, env=env,
            timeout=policy.deadline_s)
        if result.ok:
            return result
        if (classify_failure(result) == "fatal"
                or attempt == attempts - 1):
            break
        delay = policy.backoff_s(attempt, rng)
        metrics.recovery_log().record(
            "exec_retry", cmd=argv[0] if argv else "",
            attempt=attempt + 1, delay_s=round(delay, 4),
            stderr=result.stderr.strip()[-120:])
        log.warning("transient failure (%s), retry %d/%d in %.3fs: %s",
                    result.stderr.strip()[:120] or result.returncode,
                    attempt + 1, policy.max_retries, delay,
                    " ".join(argv))
        time.sleep(delay)
    if check and not result.ok:
        raise CommandError(argv, result, attempts=attempt + 1)
    return result


class Executor:
    """Interface: run an external command, optionally with stdin text.

    ``env`` adds variables on top of the inherited environment for that
    one command only (never mutates ``os.environ``). ``timeout`` is a
    per-command wall deadline: a command still running past it is
    killed and reported as ExecResult(returncode=124) — classified
    transient by the retry layer, never an exception.
    """

    def run(
        self,
        argv: Sequence[str],
        *,
        input_text: Optional[str] = None,
        check: bool = True,
        capture: bool = True,
        env: Optional[Dict[str, str]] = None,
        timeout: Optional[float] = None,
    ) -> ExecResult:
        raise NotImplementedError

    def try_run(
        self, argv: Sequence[str], *, input_text: Optional[str] = None
    ) -> ExecResult:
        """Like run() but never raises."""
        return self.run(argv, input_text=input_text, check=False)

    def have(self, binary: str) -> bool:
        """Is `binary` on PATH?"""
        raise NotImplementedError


class SystemExecutor(Executor):
    def run(
        self,
        argv: Sequence[str],
        *,
        input_text: Optional[str] = None,
        check: bool = True,
        capture: bool = True,
        env: Optional[Dict[str, str]] = None,
        timeout: Optional[float] = None,
    ) -> ExecResult:
        log.debug("exec: %s", " ".join(argv))
        full_env = None
        if env:
            import os

            full_env = {**os.environ, **env}
        try:
            proc = subprocess.run(
                list(argv),
                input=input_text,
                text=True,
                capture_output=capture,
                env=full_env,
                timeout=timeout,
            )
        except subprocess.TimeoutExpired as exc:
            # deadline kill, not a crash: report the `timeout`-style
            # exit code so classify_failure treats it as transient
            result = ExecResult(
                124, "",
                f"command timed out after {exc.timeout}s: "
                f"{' '.join(argv)}")
            if check:
                raise CommandError(argv, result) from exc
            return result
        result = ExecResult(proc.returncode, proc.stdout or "", proc.stderr or "")
        if check and not result.ok:
            raise CommandError(argv, result)
        return result

    def have(self, binary: str) -> bool:
        import shutil

        return shutil.which(binary) is not None


Responder = Callable[[List[str], Optional[str]], ExecResult]


class FakeExecutor(Executor):
    """Records commands; answers from a table of (prefix -> responder).

    ``rules`` maps a space-joined argv *prefix* to either a static
    :class:`ExecResult` or a callable ``(argv, input_text) -> ExecResult``.
    The longest matching prefix wins; unmatched commands succeed with
    empty output (so tests only specify what they care about).
    """

    def __init__(self, rules: Optional[Dict[str, object]] = None,
                 binaries: Optional[Sequence[str]] = None):
        self.rules: Dict[str, object] = dict(rules or {})
        self.calls: List[Tuple[List[str], Optional[str]]] = []
        self.binaries = set(
            binaries
            if binaries is not None
            else ["docker", "kind", "kubectl"]
        )

    def run(
        self,
        argv: Sequence[str],
        *,
        input_text: Optional[str] = None,
        check: bool = True,
        capture: bool = True,
        env: Optional[Dict[str, str]] = None,
        timeout: Optional[float] = None,
    ) -> ExecResult:
        argv = list(argv)
        self.calls.append((argv, input_text))
        joined = " ".join(argv)
        best: Optional[object] = None
        best_len = -1
        for prefix, resp in self.rules.items():
            if joined.startswith(prefix) and len(prefix) > best_len:
                best, best_len = resp, len(prefix)
        if best is None:
            result = ExecResult(0)
        elif callable(best):
            result = best(argv, input_text)
        else:
            result = best  # type: ignore[assignment]
        if check and not result.ok:
            raise CommandError(argv, result)
        return result

    def have(self, binary: str) -> bool:
        return binary in self.binaries

    # test helpers ------------------------------------------------------

    def commands(self) -> List[str]:
        return [" ".join(argv) for argv, _ in self.calls]

    def find(self, prefix: str) -> List[Tuple[List[str], Optional[str]]]:
        return [
            (argv, stdin)
            for argv, stdin in self.calls
            if " ".join(argv).startswith(prefix)
        ]
