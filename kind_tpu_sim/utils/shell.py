"""Subprocess execution layer for the orchestrator.

The reference shells out directly (``cr()`` at kind-gpu-sim.sh:64-66),
which makes it untestable without docker.  Here every external command
goes through an :class:`Executor`, so unit tests swap in
:class:`FakeExecutor` and assert on the exact command stream — the test
strategy upgrade called out in SURVEY.md §4.
"""

from __future__ import annotations

import dataclasses
import logging
import subprocess
from typing import Callable, Dict, List, Optional, Sequence, Tuple

log = logging.getLogger("kind-tpu-sim")

# Env-var prefixes of TPU-tunnel sitecustomize hooks (axon): when
# present they register themselves in EVERY new interpreter, taxing
# startup ~0.6-1.7s. CPU-only Python subprocesses strip them.
TUNNEL_ENV_PREFIXES = ("_AXON", "PALLAS_AXON")

# Warm-path knobs (docs/PERFORMANCE.md): where the XLA persistent
# compilation cache lives, and the off switch.
CACHE_DIR_ENV = "KIND_TPU_SIM_CACHE_DIR"
NO_CACHE_ENV = "KIND_TPU_SIM_NO_COMPILATION_CACHE"


def compilation_cache_dir():
    """The repo-local XLA compilation-cache directory (a pathlib.Path),
    or None when caching is disabled via NO_CACHE_ENV. Override the
    location with CACHE_DIR_ENV; default is `<repo>/.cache/jax`
    (gitignored) so psum/ring/transformer compiles amortize across
    bench and CLI invocations on the same host."""
    import os
    import pathlib

    if os.environ.get(NO_CACHE_ENV):
        return None
    override = os.environ.get(CACHE_DIR_ENV)
    if override:
        return pathlib.Path(override)
    repo = pathlib.Path(__file__).resolve().parents[2]
    return repo / ".cache" / "jax"


def compilation_cache_env() -> Dict[str, str]:
    """Env vars that point a JAX child at the persistent compilation
    cache. Empty when caching is disabled or the dir is uncreatable
    (read-only checkout): a child must never fail bring-up over a
    cache it can live without."""
    cache = compilation_cache_dir()
    if cache is None:
        return {}
    try:
        cache.mkdir(parents=True, exist_ok=True)
    except OSError:
        return {}
    return {
        "JAX_COMPILATION_CACHE_DIR": str(cache),
        # The simulator's hot programs (psum smoke, collectives)
        # compile in well under jax's 1s default threshold — cache
        # everything.
        "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS": "0",
        "JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES": "0",
    }


def cpu_subprocess_env(base: Optional[Dict[str, str]] = None
                       ) -> Dict[str, str]:
    """Copy of the environment for a CPU-only Python child, with
    TPU-tunnel startup hooks stripped (see TUNNEL_ENV_PREFIXES) and
    the persistent XLA compilation cache wired in (setdefault, so an
    explicit caller/env choice wins)."""
    import os

    env = dict(os.environ if base is None else base)
    for key in list(env):
        if key.startswith(TUNNEL_ENV_PREFIXES):
            del env[key]
    for key, value in compilation_cache_env().items():
        env.setdefault(key, value)
    return env


@dataclasses.dataclass
class ExecResult:
    returncode: int
    stdout: str = ""
    stderr: str = ""

    @property
    def ok(self) -> bool:
        return self.returncode == 0


class CommandError(RuntimeError):
    def __init__(self, argv: Sequence[str], result: ExecResult):
        self.argv = list(argv)
        self.result = result
        super().__init__(
            f"command failed ({result.returncode}): {' '.join(argv)}\n"
            f"{result.stderr.strip()}"
        )


class Executor:
    """Interface: run an external command, optionally with stdin text.

    ``env`` adds variables on top of the inherited environment for that
    one command only (never mutates ``os.environ``).
    """

    def run(
        self,
        argv: Sequence[str],
        *,
        input_text: Optional[str] = None,
        check: bool = True,
        capture: bool = True,
        env: Optional[Dict[str, str]] = None,
    ) -> ExecResult:
        raise NotImplementedError

    def try_run(
        self, argv: Sequence[str], *, input_text: Optional[str] = None
    ) -> ExecResult:
        """Like run() but never raises."""
        return self.run(argv, input_text=input_text, check=False)

    def have(self, binary: str) -> bool:
        """Is `binary` on PATH?"""
        raise NotImplementedError


class SystemExecutor(Executor):
    def run(
        self,
        argv: Sequence[str],
        *,
        input_text: Optional[str] = None,
        check: bool = True,
        capture: bool = True,
        env: Optional[Dict[str, str]] = None,
    ) -> ExecResult:
        log.debug("exec: %s", " ".join(argv))
        full_env = None
        if env:
            import os

            full_env = {**os.environ, **env}
        proc = subprocess.run(
            list(argv),
            input=input_text,
            text=True,
            capture_output=capture,
            env=full_env,
        )
        result = ExecResult(proc.returncode, proc.stdout or "", proc.stderr or "")
        if check and not result.ok:
            raise CommandError(argv, result)
        return result

    def have(self, binary: str) -> bool:
        import shutil

        return shutil.which(binary) is not None


Responder = Callable[[List[str], Optional[str]], ExecResult]


class FakeExecutor(Executor):
    """Records commands; answers from a table of (prefix -> responder).

    ``rules`` maps a space-joined argv *prefix* to either a static
    :class:`ExecResult` or a callable ``(argv, input_text) -> ExecResult``.
    The longest matching prefix wins; unmatched commands succeed with
    empty output (so tests only specify what they care about).
    """

    def __init__(self, rules: Optional[Dict[str, object]] = None,
                 binaries: Optional[Sequence[str]] = None):
        self.rules: Dict[str, object] = dict(rules or {})
        self.calls: List[Tuple[List[str], Optional[str]]] = []
        self.binaries = set(
            binaries
            if binaries is not None
            else ["docker", "kind", "kubectl"]
        )

    def run(
        self,
        argv: Sequence[str],
        *,
        input_text: Optional[str] = None,
        check: bool = True,
        capture: bool = True,
        env: Optional[Dict[str, str]] = None,
    ) -> ExecResult:
        argv = list(argv)
        self.calls.append((argv, input_text))
        joined = " ".join(argv)
        best: Optional[object] = None
        best_len = -1
        for prefix, resp in self.rules.items():
            if joined.startswith(prefix) and len(prefix) > best_len:
                best, best_len = resp, len(prefix)
        if best is None:
            result = ExecResult(0)
        elif callable(best):
            result = best(argv, input_text)
        else:
            result = best  # type: ignore[assignment]
        if check and not result.ok:
            raise CommandError(argv, result)
        return result

    def have(self, binary: str) -> bool:
        return binary in self.binaries

    # test helpers ------------------------------------------------------

    def commands(self) -> List[str]:
        return [" ".join(argv) for argv, _ in self.calls]

    def find(self, prefix: str) -> List[Tuple[List[str], Optional[str]]]:
        return [
            (argv, stdin)
            for argv, stdin in self.calls
            if " ".join(argv).startswith(prefix)
        ]
