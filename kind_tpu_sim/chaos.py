"""Deterministic chaos engine for the simulated cluster.

The reference has no fault-injection capability (SURVEY.md §5 "failure
detection — minimal"); this subsystem exceeds it deliberately, in two
tiers:

**Manual levers** (:class:`ChaosManager` — the original 87-line
surface, still the `chaos fail/heal/kill-node/start-node` CLI):

* ``fail`` / ``heal`` — drive the device plugin's health channel by
  writing device IDs into the node's unhealthy file
  (manifests.UNHEALTHY_FILE). The plugin's ListAndWatch poller picks
  the change up within ~1s and kubelet reduces the node's allocatable
  count — the durable-capacity behavior a one-shot status patch
  (kind-gpu-sim.sh:113,116) cannot model.
* ``kill-node`` / ``start-node`` — stop/start the kind node container
  itself to exercise scheduler failover of accelerator pods.

**Seeded scenario engine** (`chaos run` / `chaos soak`,
docs/CHAOS.md): :class:`ChaosSchedule` derives a :class:`FaultPlan` —
which fault kind hits which target at which step — purely from
``KIND_TPU_SIM_CHAOS_SEED``, so a failing chaos run replays exactly.
Named scenarios drive a fault plan end-to-end through a real recovery
path (exec retry/backoff, worker respawn, grid-cell requeue,
preemption checkpoint/resume, serving slot requeue) and assert the
recovery INVARIANT (results identical to fault-free, trajectory
continuous, no corrupted streams) while publishing every injected
fault and recovery action through metrics.recovery_log().
"""

from __future__ import annotations

import dataclasses
import logging
import os
import random
import zlib
from typing import Callable, Dict, List, Optional, Sequence

from kind_tpu_sim import manifests, metrics
from kind_tpu_sim.analysis import knobs
from kind_tpu_sim.cluster import ClusterManager
from kind_tpu_sim.config import SimConfig
from kind_tpu_sim.runtime import ContainerRuntime
from kind_tpu_sim.utils.shell import (
    ExecResult,
    FakeExecutor,
    RetryPolicy,
    run_with_retry,
)

log = logging.getLogger("kind-tpu-sim")

CHAOS_SEED_ENV = knobs.CHAOS_SEED

# The fault vocabulary. Each kind maps onto the layer that recovers
# from it (docs/CHAOS.md has the full matrix).
FAULT_KINDS = (
    "worker_crash",      # protocol worker os._exit mid-job
    "worker_hang",       # protocol worker wedges; deadline kill
    "device_flap",       # plugin health channel: fail then heal
    "node_kill",         # kind node container stopped
    "node_restart",      # ... and started again
    "preempt_sigterm",   # SIGTERM mid-train-step (TPU maintenance)
    "cmd_transient",     # kubectl/runtime command fails transiently
    "slot_failure",      # serving slot/engine dies mid-stream
    "replica_preempt",   # fleet: a whole serving replica preempted
    "replica_flap",      # fleet: a replica fails/heals repeatedly
    "node_drain",        # sched: node cordoned, gangs evicted+rescheduled
    "node_fail",         # sched: node breaks outright (capacity gone)
    # gray failures (docs/HEALTH.md): alive but slow — nothing
    # crashes, so only the failure detector can catch them
    "straggler_worker",  # grid: one worker stalls every job (param: s)
    "degraded_link",     # ICI link at param x nominal bandwidth
    "slow_replica",      # fleet: replica service times x param
    "flaky_node",        # intermittent sub-crash stalls (param: s)
    # blast-radius tier (docs/GLOBE.md): the failures that page
    # people — whole failure domains, not components
    "zone_loss",         # globe: every cell in a zone goes dark
    "dcn_degrade",       # inter-zone DCN link at param x nominal
    "herd_failover",     # zone dies at peak: thundering-herd spill
    "cell_drain",        # globe: cell drained for maintenance
    # overload tier (docs/OVERLOAD.md): nothing breaks — demand
    # itself is the fault, and amplification is the failure mode
    "demand_surge",      # step multiplier on arrivals (param: x)
    "retry_storm",       # client retry amplification (param: tries)
    # training tenant (docs/TRAINING.md): faults aimed at training
    # gangs — the checkpoint-economics levers
    "train_preempt",     # graceful gang preemption (guard semantics)
    "train_kill",        # hard gang kill: no grace, rollback to ckpt
    # disaggregated serving (docs/DISAGG.md): faults aimed at the
    # phase split itself — the pool boundary and the KV link
    "prefill_pool_loss",   # every prefill replica preempted at once
    "kv_transfer_degrade",  # KV link at param x nominal bandwidth
    # multi-tenancy (docs/TENANCY.md): the noisy neighbor IS the
    # fault — one tenant misbehaves, isolation must hold for the rest
    "noisy_neighbor",    # one tenant's arrivals x param
    "tenant_surge",      # windowed surge confined to one tenant
    # model zoo (docs/ZOO.md): heterogeneous fleets serving many
    # models — the swap churn and the generation-skewed capacity
    # loss are the faults the warm-pool machinery must absorb
    "model_swap_storm",  # resident models evicted in pulses (param)
    "generation_cell_drain",  # every cell of one generation drained
    # silent data corruption (docs/SDC.md): the OUTPUT, not the
    # schedule, is the casualty — a defective chip computes wrong
    # while reporting healthy, and unlike every windowed fault above
    # it persists until integrity checking names and quarantines it
    "sdc_chip",          # one chip corrupts param frac of its work
    "correlated_domain_fault",  # one rack/power domain fails whole
)


def resolve_seed(seed: Optional[int] = None) -> int:
    """Explicit seed > env (KIND_TPU_SIM_CHAOS_SEED) > 0."""
    if seed is not None:
        return int(seed)
    return int(knobs.get(CHAOS_SEED_ENV))


# Layers a fault schema may claim (the docs/CHAOS.md recovery
# matrix's row owners).
FAULT_LAYERS = ("runtime", "grid", "cluster", "engine", "fleet",
                "sched", "health", "globe", "overload", "train",
                "tenant", "zoo")


@dataclasses.dataclass(frozen=True)
class FaultSchema:
    """The machine-readable contract of one fault kind — what the
    scenario fuzzer (kind_tpu_sim/scenarios/fuzz.py, docs/FUZZ.md)
    samples from instead of ad-hoc kwargs.

    ``param`` is ``None`` (the kind has no magnitude) or a
    ``(draw, lo, hi)`` triple: ``draw`` is ``"int"``
    (``rng.randint(lo, hi)``) or ``"uniform"``
    (``round(rng.uniform(lo, hi), 3)``) — exactly the historical
    :meth:`ChaosSchedule.plan` draws, so schema-driven plans stay
    byte-identical with pre-schema ones. ``scopes`` names the sim
    topologies the kind can strike (``fleet`` / ``globe`` /
    virtual-clock-free surfaces like ``worker``); ``needs`` the
    config prerequisites (``sched``, ``training``, ``overload``,
    ``jax``); ``fuzzable`` whether the fuzzer may compose it (the
    process/engine kinds exercise real subprocesses or jitted
    engines — deterministic to run, but not expressible as timed
    virtual-clock windows); ``exclusive`` caps the kind at one per
    composed spec (a second simultaneous zone loss or demand surge
    is a different experiment, not a composition)."""

    kind: str
    layer: str
    param: Optional[tuple] = None   # (draw, lo, hi)
    param_doc: str = ""
    scopes: tuple = ()
    needs: tuple = ()
    fuzzable: bool = False
    exclusive: bool = False

    def as_dict(self) -> dict:
        return {
            "kind": self.kind,
            "layer": self.layer,
            "param": (list(self.param)
                      if self.param is not None else None),
            "param_doc": self.param_doc,
            "scopes": list(self.scopes),
            "needs": list(self.needs),
            "fuzzable": self.fuzzable,
            "exclusive": self.exclusive,
        }


# One schema per FAULT_KINDS entry — completeness is machine-checked
# (fault_schema_problems(), wired into `analysis lint` and the test
# suite the same way rule `unknown-knob` guards the knob registry).
FAULT_SCHEMAS: Dict[str, FaultSchema] = {s.kind: s for s in (
    FaultSchema("worker_crash", "grid", scopes=("worker",)),
    FaultSchema("worker_hang", "grid", param=("int", 1, 5),
                param_doc="hang seconds before the deadline kill",
                scopes=("worker",)),
    FaultSchema("device_flap", "cluster", scopes=("control-plane",)),
    FaultSchema("node_kill", "cluster", scopes=("control-plane",)),
    FaultSchema("node_restart", "cluster",
                scopes=("control-plane",)),
    FaultSchema("preempt_sigterm", "engine", scopes=("train",),
                needs=("jax",)),
    FaultSchema("cmd_transient", "runtime", param=("int", 1, 3),
                param_doc="transient failures before success",
                scopes=("control-plane",)),
    FaultSchema("slot_failure", "engine", scopes=("serving",),
                needs=("jax",)),
    FaultSchema("replica_preempt", "fleet", scopes=("fleet",),
                fuzzable=True),
    FaultSchema("replica_flap", "fleet", scopes=("fleet",),
                fuzzable=True),
    FaultSchema("node_drain", "sched", scopes=("fleet",),
                needs=("sched",), fuzzable=True),
    FaultSchema("node_fail", "sched", scopes=("fleet",),
                needs=("sched",), fuzzable=True),
    FaultSchema("straggler_worker", "health",
                param=("uniform", 1.6, 2.4),
                param_doc="per-cell stall seconds",
                scopes=("worker",)),
    FaultSchema("degraded_link", "health",
                param=("uniform", 0.08, 0.25),
                param_doc="ICI link bandwidth factor",
                scopes=("fleet",), needs=("sched",), fuzzable=True),
    FaultSchema("slow_replica", "health",
                param=("uniform", 3.0, 6.0),
                param_doc="service-time inflation factor",
                scopes=("fleet",), fuzzable=True),
    FaultSchema("flaky_node", "health",
                param=("uniform", 0.5, 1.5),
                param_doc="intermittent stall seconds",
                scopes=("worker",)),
    FaultSchema("zone_loss", "globe", scopes=("globe",),
                fuzzable=True, exclusive=True),
    FaultSchema("dcn_degrade", "globe",
                param=("uniform", 0.08, 0.25),
                param_doc="inter-zone DCN bandwidth factor",
                scopes=("globe",), fuzzable=True),
    FaultSchema("herd_failover", "globe", scopes=("globe",),
                fuzzable=True, exclusive=True),
    FaultSchema("cell_drain", "globe", scopes=("globe",),
                fuzzable=True),
    FaultSchema("demand_surge", "overload",
                param=("uniform", 3.0, 5.0),
                param_doc="arrival-rate step multiplier",
                scopes=("fleet",), needs=("overload",),
                fuzzable=True, exclusive=True),
    FaultSchema("retry_storm", "overload", param=("int", 3, 5),
                param_doc="uncontrolled client max attempts",
                scopes=("fleet",), needs=("overload",)),
    FaultSchema("train_preempt", "train", scopes=("fleet",),
                needs=("sched", "training"), fuzzable=True),
    FaultSchema("train_kill", "train", scopes=("fleet",),
                needs=("sched", "training"), fuzzable=True),
    FaultSchema("prefill_pool_loss", "fleet", scopes=("fleet",),
                needs=("disagg",), fuzzable=True, exclusive=True),
    FaultSchema("kv_transfer_degrade", "fleet",
                param=("uniform", 0.08, 0.25),
                param_doc="KV-transfer link bandwidth factor",
                scopes=("fleet",), needs=("disagg",),
                fuzzable=True),
    FaultSchema("noisy_neighbor", "tenant",
                param=("uniform", 3.0, 6.0),
                param_doc="aggressor-tenant arrival multiplier",
                scopes=("fleet",), needs=("tenancy",),
                fuzzable=True, exclusive=True),
    FaultSchema("tenant_surge", "tenant",
                param=("uniform", 2.0, 4.0),
                param_doc="one tenant's windowed rate multiplier",
                scopes=("fleet",), needs=("tenancy",),
                fuzzable=True, exclusive=True),
    FaultSchema("model_swap_storm", "zoo",
                param=("int", 2, 4),
                param_doc="resident-model eviction pulses across "
                          "the window",
                scopes=("fleet",), needs=("zoo",),
                fuzzable=True, exclusive=True),
    FaultSchema("generation_cell_drain", "zoo",
                scopes=("globe",), needs=("zoo",),
                fuzzable=True),
    # SDC kinds (docs/SDC.md) carry "sdc" in needs so the shared
    # fuzz pool skips them (the zoo-stream precedent): they are
    # drawn only from the dedicated fuzz:sdc sub-seed stream, which
    # keeps every pre-SDC fuzz draw — and every pinned replay
    # digest — byte-identical
    FaultSchema("sdc_chip", "health",
                param=("uniform", 0.2, 0.6),
                param_doc="fraction of work the defective chip "
                          "corrupts (persists until quarantined)",
                scopes=("fleet",), needs=("sdc",),
                fuzzable=True),
    FaultSchema("correlated_domain_fault", "sched",
                scopes=("fleet",), needs=("sdc", "sched"),
                fuzzable=True, exclusive=True),
)}


def draw_param(kind: str, rng: random.Random) -> float:
    """One seeded magnitude draw for ``kind``, per its schema — THE
    param semantics (ChaosSchedule.plan and the fuzzer both route
    through here, so a kind's magnitude range is declared exactly
    once)."""
    schema = FAULT_SCHEMAS[kind]
    if schema.param is None:
        return 0.0
    draw, lo, hi = schema.param
    if draw == "int":
        return float(rng.randint(int(lo), int(hi)))
    return round(rng.uniform(float(lo), float(hi)), 3)


def fault_schema_problems() -> List[str]:
    """Registry/schema cross-check (the `unknown-knob` idiom for
    fault kinds): every FAULT_KINDS entry must be schema'd, every
    schema must describe a known kind, and each schema must be
    internally coherent. Wired into `analysis lint` and the test
    suite so a new kind cannot ship samplable-but-unspecified."""
    problems: List[str] = []
    for kind in FAULT_KINDS:
        if kind not in FAULT_SCHEMAS:
            problems.append(
                f"fault kind {kind!r} has no FaultSchema "
                "(kind_tpu_sim/chaos.py FAULT_SCHEMAS)")
    for kind, schema in sorted(FAULT_SCHEMAS.items()):
        if kind not in FAULT_KINDS:
            problems.append(
                f"FaultSchema {kind!r} describes no FAULT_KINDS "
                "entry")
        if schema.kind != kind:
            problems.append(
                f"FaultSchema keyed {kind!r} names itself "
                f"{schema.kind!r}")
        if schema.layer not in FAULT_LAYERS:
            problems.append(
                f"FaultSchema {kind!r} claims unknown layer "
                f"{schema.layer!r}; known: "
                f"{', '.join(FAULT_LAYERS)}")
        if schema.param is not None:
            bad = (len(schema.param) != 3
                   or schema.param[0] not in ("int", "uniform")
                   or not schema.param[1] <= schema.param[2])
            if bad:
                problems.append(
                    f"FaultSchema {kind!r} param {schema.param!r} "
                    "is not a (draw, lo, hi) triple with draw in "
                    "int|uniform and lo <= hi")
        if schema.fuzzable and not schema.scopes:
            problems.append(
                f"FaultSchema {kind!r} is fuzzable but declares no "
                "scopes — the fuzzer cannot place it")
        if schema.exclusive and not schema.fuzzable:
            problems.append(
                f"FaultSchema {kind!r} is exclusive but not "
                "fuzzable — exclusivity only constrains the fuzzer")
    return problems


@dataclasses.dataclass(frozen=True)
class FaultEvent:
    """One planned fault: ``kind`` strikes ``target`` at schedule
    index ``at`` (the unit — step, round, request number — belongs to
    the scenario consuming the plan). ``param`` carries the kind's
    magnitude (hang seconds, transient-failure count...)."""

    kind: str
    at: int
    target: int = 0
    param: float = 0.0

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """An ordered, immutable fault schedule — the replayable artifact
    a chaos run is defined by."""

    seed: int
    events: tuple

    def for_kind(self, kind: str) -> List[FaultEvent]:
        return [e for e in self.events if e.kind == kind]

    def as_dict(self) -> dict:
        return {"seed": self.seed,
                "events": [e.as_dict() for e in self.events]}


class ChaosSchedule:
    """Seeded fault-plan generator: the same ``seed`` and arguments
    produce the IDENTICAL plan, always — determinism is the whole
    point (a chaos failure you cannot replay is a flake, not a
    finding). Each ``plan()`` derives its own sub-seed from the
    canonical argument repr, so two plans with different shapes never
    share a stream and argument order cannot perturb results."""

    def __init__(self, seed: Optional[int] = None):
        self.seed = resolve_seed(seed)

    def plan(self, kinds: Sequence[str] = ("worker_crash",),
             n_faults: int = 1, horizon: int = 8,
             targets: int = 2) -> FaultPlan:
        """``n_faults`` events drawn over ``horizon`` schedule slots
        and ``targets`` possible victims, kinds cycled through the
        seeded stream. ``param`` is drawn per kind from its
        :data:`FAULT_SCHEMAS` range (hang seconds in [1, 5],
        transient counts in [1, 3], straggler/flaky stall seconds,
        slow-replica service factors, degraded-link bandwidth
        factors — else 0)."""
        for kind in kinds:
            if kind not in FAULT_KINDS:
                raise ValueError(
                    f"unknown fault kind {kind!r}; known: "
                    f"{', '.join(FAULT_KINDS)}")
        key = repr((self.seed, tuple(kinds), int(n_faults),
                    int(horizon), int(targets)))
        rng = random.Random(zlib.crc32(key.encode("utf-8")))
        events = []
        for _ in range(n_faults):
            kind = rng.choice(list(kinds))
            # param is drawn BEFORE the slot/target draws — the
            # historical stream order, which schema-driven plans
            # must reproduce byte-identically
            param = draw_param(kind, rng)
            events.append(FaultEvent(
                kind=kind,
                at=rng.randrange(max(1, horizon)),
                target=rng.randrange(max(1, targets)),
                param=param,
            ))
        events.sort(key=lambda e: (e.at, e.target, e.kind))
        return FaultPlan(seed=self.seed, events=tuple(events))


class FlakyExecutor(FakeExecutor):
    """FakeExecutor that injects TRANSIENT failures from a fault
    plan: commands matching ``flaky_prefix`` fail their first
    ``fail_attempts`` invocations with a retryable error, then
    delegate to the normal rule table. The unit under test is the
    retry layer (shell.run_with_retry): the command stream must
    complete as if nothing happened, with the retries observable in
    metrics.recovery_log()."""

    def __init__(self, rules=None, binaries=None,
                 flaky_prefix: str = "kubectl",
                 fail_attempts: int = 2,
                 error_text: str = ("Unable to connect to the server: "
                                    "dial tcp 127.0.0.1:6443: connect:"
                                    " connection refused")):
        super().__init__(rules, binaries)
        self.flaky_prefix = flaky_prefix
        self.fail_attempts = fail_attempts
        self.error_text = error_text
        self.injected_failures = 0
        self._attempts: Dict[str, int] = {}

    def run(self, argv, *, input_text=None, check=True, capture=True,
            env=None, timeout=None):
        joined = " ".join(argv)
        if joined.startswith(self.flaky_prefix):
            seen = self._attempts.get(joined, 0)
            if seen < self.fail_attempts:
                self._attempts[joined] = seen + 1
                self.injected_failures += 1
                self.calls.append((list(argv), input_text))
                result = ExecResult(1, "", self.error_text)
                if check and not result.ok:
                    from kind_tpu_sim.utils.shell import CommandError

                    raise CommandError(argv, result)
                return result
        return super().run(argv, input_text=input_text, check=check,
                           capture=capture, env=env, timeout=timeout)


class ChaosManager:
    def __init__(self, cfg: SimConfig, runtime: ContainerRuntime,
                 cluster: ClusterManager):
        self.cfg = cfg
        self.rt = runtime
        self.cluster = cluster

    def resolve_node(self, node: Optional[str],
                     worker: Optional[int]) -> str:
        if node:
            return node
        workers = self.cluster.worker_nodes()
        if worker is None:
            raise ValueError("specify --node or --worker")
        if not 0 <= worker < len(workers):
            raise ValueError(
                f"--worker {worker} out of range ({len(workers)} workers)"
            )
        return workers[worker]

    def fail_devices(self, node: str, device_ids: List[str]) -> None:
        """Mark devices unhealthy on a node (empty list = all).

        Device IDs come from ``MultiSlice.device_ids`` on the node's
        GLOBAL worker index — the job-level scheme the plugin uses
        (``DevicePlugin::DeviceIds``), valid on every slice of a
        ``--num-slices > 1`` cluster (per-slice ``Slice.device_ids``
        would reject nodes of slice >= 1).
        """
        if not device_ids:
            workers = self.cluster.worker_nodes()
            device_ids = self.cfg.multislice.device_ids(
                workers.index(node))
        content = "\n".join(device_ids) + "\n"
        self.rt.run(
            "exec", node, "mkdir", "-p", manifests.SIM_STATE_DIR
        )
        self.rt.run(
            "exec", "-i", node, "sh", "-c",
            f"cat > {manifests.UNHEALTHY_FILE}",
            input_text=content,
        )
        log.info("marked %d device(s) unhealthy on %s",
                 len(device_ids), node)

    def heal(self, node: str) -> None:
        self.rt.run(
            "exec", node, "sh", "-c",
            f"rm -f {manifests.UNHEALTHY_FILE}",
        )
        log.info("healed all devices on %s", node)

    def kill_node(self, node: str) -> None:
        self.rt.run("stop", node)
        log.info("stopped node container %s", node)

    def start_node(self, node: str) -> None:
        self.rt.run("start", node)
        log.info("started node container %s", node)


# ---------------------------------------------------------------------
# named scenarios (the `chaos run` / `chaos soak` surface)


@dataclasses.dataclass(frozen=True)
class Scenario:
    name: str
    fn: Callable[[int], dict]
    description: str
    needs_jax: bool = False
    slow: bool = False


SCENARIOS: Dict[str, Scenario] = {}


def _scenario(name: str, description: str, needs_jax: bool = False,
              slow: bool = False):
    def register(fn):
        SCENARIOS[name] = Scenario(name, fn, description,
                                   needs_jax=needs_jax, slow=slow)
        return fn

    return register


def _fake_chaos_manager(num_slices: int = 1) -> ChaosManager:
    """A ChaosManager over the dry-run control plane — scenario
    plumbing for the device/node fault kinds, no daemon needed."""
    from kind_tpu_sim.fakes import dry_run_executor
    from kind_tpu_sim.registry import LocalRegistry
    from kind_tpu_sim.runtime import detect_runtime

    cfg = SimConfig(runtime="fake", num_slices=num_slices)
    executor = dry_run_executor(cfg)
    # detect_runtime('fake') binds the SAME recording executor, so
    # scenarios can assert on the full command stream afterwards
    runtime = detect_runtime(executor, prefer="fake")
    cluster = ClusterManager(cfg, runtime,
                             LocalRegistry(cfg, runtime))
    return ChaosManager(cfg, runtime, cluster)


@_scenario("flaky-exec",
           "transient kubectl failures recovered by the classified "
           "retry policy (exponential backoff + jitter)")
def _scenario_flaky_exec(seed: int) -> dict:
    plan = ChaosSchedule(seed).plan(kinds=("cmd_transient",),
                                    n_faults=2, horizon=4, targets=1)
    fail_attempts = max(1, int(plan.events[0].param))
    fake = FlakyExecutor(fail_attempts=fail_attempts)
    policy = RetryPolicy(max_retries=3, base_ms=1.0, seed=seed)
    commands = (["kubectl", "get", "nodes", "-o", "jsonpath={..}"],
                ["kubectl", "get", "pods", "-A", "-o", "json"])
    results = [run_with_retry(fake, argv, policy=policy)
               for argv in commands]
    ok = all(r.ok for r in results)
    return {
        "plan": plan.as_dict(),
        "injected_failures": fake.injected_failures,
        "commands_completed": sum(1 for r in results if r.ok),
        "ok": bool(ok and fake.injected_failures
                   == fail_attempts * len(commands)),
    }


@_scenario("worker-crash-grid",
           "a slice worker killed mid-sweep; its grid cells requeue "
           "on survivors and results match the fault-free run")
def _scenario_worker_crash_grid(seed: int) -> dict:
    from kind_tpu_sim.parallel import multihost

    plan = ChaosSchedule(seed).plan(kinds=("worker_crash",),
                                    n_faults=1, horizon=6, targets=2)
    ev = plan.events[0]
    cells = [{"cell": i, "payload": seed} for i in range(6)]
    clean, _ = multihost.scatter_grid_cells(
        cells, workers=2, timeout=120.0)
    faulted, stats = multihost.scatter_grid_cells(
        cells, workers=2, timeout=120.0,
        fault=("crash", ev.at % len(cells)))
    return {
        "plan": plan.as_dict(),
        "cells": len(cells),
        "faults_injected": stats["faults_injected"],
        "requeues": stats["requeues"],
        "respawns": stats["respawns"],
        "results_identical": faulted == clean,
        "ok": bool(faulted == clean
                   and stats["faults_injected"] == 1
                   and stats["requeues"] >= 1),
    }


@_scenario("worker-hang-grid",
           "a slice worker wedges mid-cell; the deadline kill "
           "requeues its cell and the sweep still completes")
def _scenario_worker_hang_grid(seed: int) -> dict:
    from kind_tpu_sim.parallel import multihost

    plan = ChaosSchedule(seed).plan(kinds=("worker_hang",),
                                    n_faults=1, horizon=5, targets=2)
    ev = plan.events[0]
    cells = [{"cell": i, "payload": seed} for i in range(5)]
    clean, _ = multihost.scatter_grid_cells(
        cells, workers=2, timeout=120.0)
    faulted, stats = multihost.scatter_grid_cells(
        cells, workers=2, timeout=120.0, cell_timeout=3.0,
        fault=("hang", ev.at % len(cells), ev.param * 20))
    return {
        "plan": plan.as_dict(),
        "cells": len(cells),
        "faults_injected": stats["faults_injected"],
        "requeues": stats["requeues"],
        "results_identical": faulted == clean,
        "ok": bool(faulted == clean
                   and stats["faults_injected"] == 1
                   and stats["requeues"] >= 1),
    }


@_scenario("device-flap",
           "seeded fail/heal cycles through the device plugin's "
           "health channel (dry-run control plane)")
def _scenario_device_flap(seed: int) -> dict:
    plan = ChaosSchedule(seed).plan(kinds=("device_flap",),
                                    n_faults=3, horizon=6, targets=2)
    mgr = _fake_chaos_manager()
    workers = mgr.cluster.worker_nodes()
    flaps = 0
    for ev in plan.events:
        node = workers[ev.target % len(workers)]
        mgr.fail_devices(node, [])
        mgr.heal(node)
        flaps += 1
        metrics.recovery_log().record("device_flap", node=node)
    cmds = mgr.rt.executor.commands()
    fails = sum(1 for c in cmds if "cat >" in c or "exec -i" in c)
    heals = sum(1 for c in cmds
                if f"rm -f {manifests.UNHEALTHY_FILE}" in c)
    return {
        "plan": plan.as_dict(),
        "flaps": flaps,
        "fail_writes": fails,
        "heal_writes": heals,
        # every flap must end healed — the recovery invariant
        "ok": bool(flaps == len(plan.events) and heals == flaps),
    }


@_scenario("node-flap",
           "seeded kill/restart cycles of kind node containers "
           "(dry-run control plane)")
def _scenario_node_flap(seed: int) -> dict:
    plan = ChaosSchedule(seed).plan(
        kinds=("node_kill", "node_restart"), n_faults=4, horizon=8,
        targets=2)
    mgr = _fake_chaos_manager()
    workers = mgr.cluster.worker_nodes()
    killed: List[str] = []
    for ev in plan.events:
        node = workers[ev.target % len(workers)]
        if ev.kind == "node_kill":
            mgr.kill_node(node)
            killed.append(node)
            metrics.recovery_log().record("node_kill", node=node)
        else:
            mgr.start_node(node)
            metrics.recovery_log().record("node_restart", node=node)
    # recovery invariant: every killed node is restarted before the
    # scenario ends, whatever order the plan drew. sorted(): set
    # order is hash-seed noise, and these restarts drive the
    # recorded command stream scenarios assert on byte-for-byte.
    for node in sorted(set(killed)):
        mgr.start_node(node)
    cmds = mgr.rt.executor.commands()
    stops = [c for c in cmds if c.startswith("docker stop")]
    starts = [c for c in cmds if c.startswith("docker start")]
    ok = all(any(s.endswith(node) for s in starts)
             for node in sorted(set(killed)))
    return {
        "plan": plan.as_dict(),
        "kills": len(stops),
        "restarts": len(starts),
        "ok": bool(ok),
    }


@_scenario("preempt-train",
           "SIGTERM mid-step; checkpoint written, resume reproduces "
           "the uninterrupted loss trajectory", needs_jax=True,
           slow=True)
def _scenario_preempt_train(seed: int) -> dict:
    import signal
    import tempfile

    from kind_tpu_sim.models import checkpoint as ckpt
    from kind_tpu_sim.models import transformer as tf

    plan = ChaosSchedule(seed).plan(kinds=("preempt_sigterm",),
                                    n_faults=1, horizon=5, targets=1)
    kill_step = plan.events[0].at + 1
    total = 8
    cfg = tf.ModelConfig(vocab_size=64, d_model=32, n_heads=2,
                         n_layers=2, d_ff=64, max_seq=16)
    with tempfile.TemporaryDirectory() as tmp:
        straight_dir = os.path.join(tmp, "straight")
        chaos_dir = os.path.join(tmp, "chaos")
        _, straight = ckpt.train_with_checkpointing(
            cfg, straight_dir, total_steps=total,
            checkpoint_every=total)

        def preempt(step: int) -> None:
            if step == kill_step:
                os.kill(os.getpid(), signal.SIGTERM)

        preempted_at = None
        try:
            ckpt.train_with_checkpointing(
                cfg, chaos_dir, total_steps=total,
                checkpoint_every=total, on_step=preempt)
        except ckpt.Preempted as exc:
            preempted_at = exc.step
            losses = exc.losses
        else:
            losses = {}
        _, resumed = ckpt.train_with_checkpointing(
            cfg, chaos_dir, total_steps=total,
            checkpoint_every=total)
        combined = {**losses, **resumed}
        drift = max(abs(combined[i] - straight[i])
                    for i in range(total))
    return {
        "plan": plan.as_dict(),
        "preempted_at_step": preempted_at,
        "resume_max_loss_drift": drift,
        "ok": bool(preempted_at == kill_step + 1 and drift == 0.0),
    }


@_scenario("serving-slot-failure",
           "a serving slot dies mid-stream; its request requeues and "
           "every accepted request completes uncorrupted",
           needs_jax=True, slow=True)
def _scenario_serving_slot_failure(seed: int) -> dict:
    import jax
    import numpy as np

    from kind_tpu_sim.models import transformer as tf
    from kind_tpu_sim.models.serving import (
        Request,
        ServingConfig,
        ServingEngine,
    )

    plan = ChaosSchedule(seed).plan(kinds=("slot_failure",),
                                    n_faults=1, horizon=2, targets=2)
    ev = plan.events[0]
    cfg = tf.ModelConfig(vocab_size=64, d_model=32, n_heads=2,
                         n_layers=2, d_ff=64, max_seq=64)
    params = tf.init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.RandomState(seed)
    prompts = [rng.randint(0, cfg.vocab_size,
                           size=4 + 3 * i).tolist()
               for i in range(4)]
    sc = ServingConfig(max_slots=2, max_len=48, chunk=8)

    def run(inject: bool):
        eng = ServingEngine(params, cfg, sc)
        for i, p in enumerate(prompts):
            # max_new > 2 chunks so the injected failure lands on a
            # slot that is still mid-stream (a real displacement)
            eng.submit(Request(f"c{i}", p, max_new=20,
                               seed=seed + i))
        if inject:
            for _ in range(ev.at + 1):
                eng.step_round()
            eng.inject_slot_failure(ev.target)
            eng.restore_slot(ev.target)
        comps = eng.poll() + eng.run()
        return ({c.request_id: tuple(c.tokens) for c in comps}, eng)

    clean, _ = run(inject=False)
    faulted, eng = run(inject=True)
    return {
        "plan": plan.as_dict(),
        "requests": len(prompts),
        "slot_failures": eng.slot_failures,
        "requeues": eng.requeues,
        "streams_identical": faulted == clean,
        "ok": bool(faulted == clean and eng.slot_failures == 1
                   and eng.requeues >= 1),
    }


@_scenario("fleet-flaky-replica",
           "a fleet replica fails and heals repeatedly under seeded "
           "open-loop traffic; every request still completes and "
           "post-recovery SLO attainment matches the fault-free run")
def _scenario_fleet_flaky_replica(seed: int) -> dict:
    from kind_tpu_sim import fleet

    plan = ChaosSchedule(seed).plan(kinds=("replica_flap",),
                                    n_faults=2, horizon=8, targets=3)
    spec = fleet.WorkloadSpec(process="poisson", rps=300.0,
                              n_requests=120, prompt_len=(8, 24),
                              max_new=(4, 12))
    trace = fleet.generate_trace(spec, seed)
    sim_cfg = fleet.SimReplicaConfig(max_slots=4,
                                     prefill_per_tok_s=0.002,
                                     tpot_s=0.002)
    fc = fleet.FleetConfig(replicas=3, policy="least-outstanding",
                           tick_s=0.01, sim=sim_cfg,
                           slo=fleet.SloPolicy(ttft_s=1.0,
                                               e2e_s=5.0))
    clean = fleet.FleetSim(fc, trace).run()
    span = clean["virtual_s"]
    events = []
    last_restore = 0.0
    for ev in plan.events:
        # flaps land in the first 60% of the clean makespan so
        # arrivals keep coming after the final heal (the recovery
        # window the invariant is judged over)
        at = round((ev.at + 1) / 9.0 * span * 0.6, 6)
        heal = round(at + 0.05 * span, 6)
        events.append(fleet.ChaosEvent(at_s=at, action="preempt",
                                       target=ev.target % 3))
        events.append(fleet.ChaosEvent(at_s=heal, action="restore",
                                       target=ev.target % 3))
        last_restore = max(last_restore, heal)
    faulted = fleet.FleetSim(fc, trace, chaos_events=events).run()
    tail_clean = fleet.attainment_over(clean["completions"],
                                       last_restore)
    tail_faulted = fleet.attainment_over(faulted["completions"],
                                         last_restore)
    tokens = lambda rep: sum(e["tokens"] for e in rep["completions"])  # noqa: E731
    recovered = (tail_clean is None or tail_faulted is None
                 or tail_faulted >= tail_clean)
    return {
        "plan": plan.as_dict(),
        "requests": len(trace),
        "flaps": len(plan.events),
        "requeues": faulted["router"]["requeues"],
        "tail_attainment_clean": tail_clean,
        "tail_attainment_faulted": tail_faulted,
        "ok": bool(faulted["ok"] and clean["ok"]
                   and tokens(faulted) == tokens(clean)
                   and recovered),
    }


@_scenario("disagg-pool-loss",
           "a disaggregated fleet loses its whole prefill pool "
           "mid-traffic, then its KV link degrades; the decode pool "
           "keeps finishing already-prefilled work through the "
           "outage, zero requests are lost, and post-heal SLO "
           "attainment recovers to baseline")
def _scenario_disagg_pool_loss(seed: int) -> dict:
    from kind_tpu_sim import fleet

    plan = ChaosSchedule(seed).plan(kinds=("kv_transfer_degrade",),
                                    n_faults=1, horizon=8, targets=1)
    factor = plan.events[0].param
    spec = fleet.WorkloadSpec(process="poisson", rps=120.0,
                              n_requests=100, prompt_len=(8, 24),
                              max_new=(8, 16))
    trace = fleet.generate_trace(spec, seed)
    dis = fleet.DisaggConfig(prefill_replicas=2, decode_replicas=2)
    fc = fleet.FleetConfig(replicas=4, policy="least-outstanding",
                           tick_s=0.01, disagg=dis,
                           slo=fleet.SloPolicy(ttft_s=1.0,
                                               e2e_s=5.0))
    clean = fleet.FleetSim(fc, trace).run()
    span = clean["virtual_s"]
    loss = round(span * 0.3, 6)
    heal = round(span * 0.45, 6)
    last_restore = round(span * 0.65, 6)
    events = [
        fleet.ChaosEvent(at_s=loss, action="prefill_pool_loss",
                         target=0),
        fleet.ChaosEvent(at_s=heal, action="prefill_pool_restore",
                         target=0),
        fleet.ChaosEvent(at_s=round(span * 0.5, 6),
                         action="kv_degrade", target=0,
                         param=factor),
        fleet.ChaosEvent(at_s=last_restore, action="kv_restore",
                         target=0),
    ]
    faulted = fleet.FleetSim(fc, trace, chaos_events=events).run()
    # the disagg claim: requests whose KV crossed before the loss
    # keep FINISHING inside the outage — a unified fleet at the same
    # loss fraction would stall them behind the re-prefill queue
    survivors = sum(1 for e in faulted["completions"]
                    if loss <= e["finish_s"] < heal
                    and e["finish_reason"] == "length")
    tokens = lambda rep: sum(e["tokens"] for e in rep["completions"])  # noqa: E731
    tail_clean = fleet.attainment_over(clean["completions"],
                                       last_restore)
    tail_faulted = fleet.attainment_over(faulted["completions"],
                                         last_restore)
    recovered = (tail_clean is None or tail_faulted is None
                 or tail_faulted >= tail_clean)
    return {
        "plan": plan.as_dict(),
        "requests": len(trace),
        "kv_factor": factor,
        "decode_survivors": survivors,
        "requeues": faulted["router"]["requeues"],
        "kv": faulted["disagg"]["kv"],
        "tail_attainment_clean": tail_clean,
        "tail_attainment_faulted": tail_faulted,
        "ok": bool(faulted["ok"] and clean["ok"]
                   and survivors > 0
                   and tokens(faulted) == tokens(clean)
                   and recovered),
    }


@_scenario("tenant-noisy-neighbor",
           "the batch tenant floods a tenanted fleet mid-window; "
           "per-tenant quotas throttle the aggressor, weighted-fair "
           "queuing holds the interactive victim's p99 near its "
           "alone-run, zero requests are lost, and the isolation-off "
           "contrast is reported alongside")
def _scenario_tenant_noisy_neighbor(seed: int) -> dict:
    from kind_tpu_sim import fleet
    from kind_tpu_sim.fleet import tenancy as tenancy_mod

    plan = ChaosSchedule(seed).plan(kinds=("noisy_neighbor",),
                                    n_faults=1, horizon=8, targets=1)
    mult = plan.events[0].param
    ten = tenancy_mod.default_tenancy()
    spec = fleet.WorkloadSpec(process="poisson", rps=90.0,
                              n_requests=240, prompt_len=(4, 16),
                              max_new=(4, 10), tenancy=ten)
    base = fleet.generate_trace(spec, seed)
    span = max(r.arrival_s for r in base)
    t0 = round(span * 0.3, 6)
    t1 = round(span * 0.7, 6)
    flood = tenancy_mod.tenant_surge_trace(spec, seed, t0, t1,
                                           mult, "bronze")
    slo = fleet.SloPolicy(ttft_s=1.0, e2e_s=5.0)
    # enforcement config: same tenant population (the traffic
    # signature covers only traffic-shaping fields, so the trace is
    # unchanged) but a tighter batch quota and a finer DRR quantum —
    # the admission bursts the stock burst allows are exactly the
    # slot-occupancy spikes that would bleed into the victim's p99
    enforce = tenancy_mod.TenancyConfig(
        tenants=tuple(
            (dataclasses.replace(t, quota_rps=22.0, quota_burst=3.0)
             if t.name == "bronze" else t)
            for t in ten.tenants),
        drr_quantum=1.0)
    cfg = fleet.FleetConfig(replicas=3, policy="least-outstanding",
                            slo=slo, tenancy=enforce)
    # the victim's alone-run: the interactive tenant's own trace on
    # the same fleet, nobody else admitted — its entitled latency
    alone = fleet.FleetSim(
        cfg, [r for r in base if r.tenant == "gold"]).run()
    noisy = fleet.FleetSim(cfg, flood).run()
    replay = fleet.FleetSim(cfg, tenancy_mod.tenant_surge_trace(
        spec, seed, t0, t1, mult, "bronze")).run()
    # the contrast column: same flood, isolation off (FIFO router,
    # no quotas enforced at admission) — reported, not gated
    off_cfg = fleet.FleetConfig(
        replicas=3, policy="least-outstanding", slo=slo,
        tenancy=tenancy_mod.TenancyConfig(tenants=enforce.tenants,
                                          isolation=False))
    off = fleet.FleetSim(off_cfg, flood).run()

    def victim_p99(rep: dict) -> Optional[float]:
        gold = rep["tenancy"]["slo"].get("gold", {})
        return gold.get("e2e", {}).get("p99_s")

    p99_alone = victim_p99(alone)
    p99_noisy = victim_p99(noisy)
    p99_off = victim_p99(off)
    ratio = (round(p99_noisy / p99_alone, 6)
             if p99_alone and p99_noisy is not None else None)
    bronze = noisy["tenancy"]["tenants"]["bronze"]
    return {
        "plan": plan.as_dict(),
        "requests": len(flood),
        "multiplier": mult,
        "victim_p99_alone_s": p99_alone,
        "victim_p99_noisy_s": p99_noisy,
        "victim_p99_isolation_off_s": p99_off,
        "victim_p99_ratio": ratio,
        "aggressor_quota_shed": bronze["quota_shed"],
        "aggressor_admitted": bronze["admitted"],
        "fair_queue_rounds":
            noisy["router"]["fair_queue"]["rounds"],
        "replay_identical": noisy == replay,
        "ok": bool(noisy["ok"] and alone["ok"]
                   and noisy == replay
                   and bronze["quota_shed"] >= 1
                   and ratio is not None and ratio <= 1.25),
    }


@_scenario("zoo-swap-storm",
           "a mixed v5e/v5p fleet serving the default model zoo "
           "under model-swap-storm pulses: every resident model is "
           "evicted repeatedly mid-window, the warm pool rebuilds "
           "through the swap lane each time, zero requests are "
           "lost, the swap ledger accounts every reload, and p99 "
           "holds within 1.25x of the steady-mix run")
def _scenario_zoo_swap_storm(seed: int) -> dict:
    from kind_tpu_sim import fleet
    from kind_tpu_sim.fleet import zoo as zoo_mod

    plan = ChaosSchedule(seed).plan(kinds=("model_swap_storm",),
                                    n_faults=1, horizon=8, targets=1)
    pulses = max(1, int(plan.events[0].param))
    zoo = zoo_mod.default_zoo()
    # the trace is long on purpose: a storm pulse makes each replica
    # pay ONE weight reload, so with 2-4 pulses over 6 replicas the
    # swap-delayed requests stay under 1% of 2000 — the p99 bound
    # asserts the warm pool rebuilds fast enough that the storm
    # never leaks into the tail, not that swaps are free
    spec = fleet.WorkloadSpec(process="poisson", rps=120.0,
                              n_requests=2000, prompt_len=(4, 16),
                              max_new=(16, 32), zoo=zoo)
    trace = fleet.generate_trace(spec, seed)
    span = max(r.arrival_s for r in trace)
    t0 = round(span * 0.3, 6)
    t1 = round(span * 0.7, 6)
    cfg = fleet.FleetConfig(
        replicas=6, policy="least-outstanding",
        slo=fleet.SloPolicy(ttft_s=1.0, e2e_s=5.0),
        zoo=zoo, generations=("v5e", "v5p"))

    def storm_events():
        out = []
        for k in range(pulses):
            frac = k / max(1, pulses - 1) if pulses > 1 else 0.0
            out.append(fleet.ChaosEvent(
                round(t0 + (t1 - t0) * frac, 6),
                "model_swap_evict", 0))
        return out

    steady = fleet.FleetSim(cfg, trace).run()
    storm = fleet.FleetSim(cfg, trace,
                           chaos_events=storm_events()).run()
    replay = fleet.FleetSim(cfg, trace,
                            chaos_events=storm_events()).run()

    def p99(rep: dict) -> Optional[float]:
        return rep["slo"].get("e2e", {}).get("p99_s")

    tokens = lambda rep: sum(e["tokens"] for e in rep["completions"])  # noqa: E731
    p99_steady = p99(steady)
    p99_storm = p99(storm)
    ratio = (round(p99_storm / p99_steady, 6)
             if p99_steady and p99_storm is not None else None)
    return {
        "plan": plan.as_dict(),
        "requests": len(trace),
        "pulses": pulses,
        "generations": sorted(set(storm["generations"].values())),
        "swaps_steady": steady["zoo"]["swaps"]["completed"],
        "swaps_storm": storm["zoo"]["swaps"]["completed"],
        "per_model_slo": {
            name: board.get("e2e", {}).get("p99_s")
            for name, board in storm["zoo"]["per_model_slo"]
            .items()},
        "p99_steady_s": p99_steady,
        "p99_storm_s": p99_storm,
        "p99_ratio": ratio,
        "replay_identical": storm == replay,
        "ok": bool(storm["ok"] and steady["ok"]
                   and storm == replay
                   and tokens(storm) == tokens(steady)
                   and storm["zoo"]["swaps"]["completed"]
                   >= steady["zoo"]["swaps"]["completed"]
                   and ratio is not None and ratio <= 1.25),
    }


@_scenario("fleet-preemption",
           "a serving replica (real engines) preempted mid-traffic; "
           "the router drains + requeues via the slot-failure "
           "machinery, streams stay identical to fault-free, and "
           "SLO attainment recovers to baseline", needs_jax=True,
           slow=True)
def _scenario_fleet_preemption(seed: int) -> dict:
    import jax

    from kind_tpu_sim import fleet
    from kind_tpu_sim.models import transformer as tf
    from kind_tpu_sim.models.serving import (
        ServingConfig,
        ServingEngine,
    )

    plan = ChaosSchedule(seed).plan(kinds=("replica_preempt",),
                                    n_faults=1, horizon=4, targets=2)
    target = plan.events[0].target % 2
    cfg = tf.ModelConfig(vocab_size=64, d_model=32, n_heads=2,
                         n_layers=2, d_ff=64, max_seq=64)
    params = tf.init_params(jax.random.PRNGKey(0), cfg)
    spec = fleet.WorkloadSpec(process="poisson", rps=150.0,
                              n_requests=14, prompt_len=(3, 8),
                              max_new=(6, 12), vocab=cfg.vocab_size)
    trace = fleet.generate_trace(spec, seed)
    tick = 0.05

    def run(events):
        clock = fleet.VirtualClock()

        def factory(rid):
            return fleet.EngineReplica(rid, ServingEngine(
                params, cfg,
                ServingConfig(max_slots=2, max_len=48, chunk=4),
                clock=clock.now))

        fc = fleet.FleetConfig(replicas=2, policy="round-robin",
                               tick_s=tick,
                               slo=fleet.SloPolicy(ttft_s=1.0,
                                                   e2e_s=5.0))
        return fleet.FleetSim(fc, trace, replica_factory=factory,
                              chaos_events=events,
                              clock=clock).run()

    clean = run([])
    # preempt just after a mid-trace dispatch onto the target
    # replica: the runs are identical up to that instant, so the
    # victim provably holds in-flight work — the displacement (and
    # its requeue) is guaranteed, not seed-lucky
    victim_disp = sorted(
        e["dispatch_s"] for e in clean["completions"]
        if e["replica"] == target)
    at = (victim_disp[len(victim_disp) // 4] + tick / 2
          if victim_disp else tick)
    restore = at + 4 * tick
    faulted = run([
        fleet.ChaosEvent(at_s=round(at, 6), action="preempt",
                         target=target),
        fleet.ChaosEvent(at_s=round(restore, 6), action="restore",
                         target=target),
    ])
    crc = lambda rep: {e["request_id"]: e["tokens_crc"]  # noqa: E731
                       for e in rep["completions"]}
    tail_clean = fleet.attainment_over(clean["completions"], restore)
    tail_faulted = fleet.attainment_over(faulted["completions"],
                                         restore)
    recovered = (tail_clean is None or tail_faulted is None
                 or tail_faulted >= tail_clean)
    return {
        "plan": plan.as_dict(),
        "requests": len(trace),
        "preempted_replica": target,
        "preempt_at_s": round(at, 6),
        "requeues": faulted["router"]["requeues"],
        "streams_identical": crc(faulted) == crc(clean),
        "tail_attainment_clean": tail_clean,
        "tail_attainment_faulted": tail_faulted,
        "ok": bool(faulted["ok"] and clean["ok"]
                   and crc(faulted) == crc(clean)
                   and faulted["router"]["requeues"] >= 1
                   and recovered),
    }


@_scenario("sched-node-drain",
           "a TPU node drained mid-traffic under the scheduler-"
           "backed fleet: its replica's gang evicts, reschedules "
           "onto surviving nodes, warms up, and post-recovery SLO "
           "attainment matches the fault-free run")
def _scenario_sched_node_drain(seed: int) -> dict:
    from kind_tpu_sim import fleet

    plan = ChaosSchedule(seed).plan(kinds=("node_drain",),
                                    n_faults=1, horizon=4, targets=4)
    ev = plan.events[0]
    # arrivals span ~4 virtual seconds — long enough that the
    # evicted gang's rebind + bind latency + 0.55s warm-up all land
    # WELL before the last third of the trace (the judged window)
    spec = fleet.WorkloadSpec(process="poisson", rps=60.0,
                              n_requests=240, prompt_len=(8, 24),
                              max_new=(4, 12))
    trace = fleet.generate_trace(spec, seed)
    sim_cfg = fleet.SimReplicaConfig(max_slots=4,
                                     prefill_per_tok_s=0.002,
                                     tpot_s=0.002)
    fc = fleet.FleetConfig(replicas=2, policy="least-outstanding",
                           tick_s=0.01, sim=sim_cfg,
                           slo=fleet.SloPolicy(ttft_s=1.0,
                                               e2e_s=5.0),
                           sched=fleet.FleetSchedConfig())
    clean = fleet.FleetSim(fc, trace).run()
    # drain a node that PROVABLY hosts a replica gang (the runs are
    # identical up to the drain instant, so the clean run's t=0
    # placement names the victim) — displacement is guaranteed, not
    # seed-lucky; ChaosEvent.target is the node's index in the
    # sorted inventory, the same resolution FleetSim applies
    victim_replica = ev.target % fc.replicas
    placed = next(
        e for e in clean["scheduler"]["events"]
        if e["type"] == "Scheduled"
        and e["gang"] == f"replica-{victim_replica}")
    node_names = sorted(
        n["name"]
        for d in fleet.FleetSim(fc, []).sched.inv.as_dict()[
            "domains"].values()
        for n in d["nodes"])
    target = node_names.index(placed["nodes"][0])
    # the drain lands a third into the arrival window and the node
    # restores at two thirds — a full third of the trace arrives
    # post-restore, so the recovery window has real traffic to judge
    arr_max = max(r.arrival_s for r in trace)
    at = round(arr_max / 3.0, 6)
    restore = round(2.0 * arr_max / 3.0, 6)
    events = [
        fleet.ChaosEvent(at_s=at, action="node_drain",
                         target=target),
        fleet.ChaosEvent(at_s=restore, action="node_restore",
                         target=target),
    ]
    faulted = fleet.FleetSim(fc, trace, chaos_events=events).run()
    tail_clean = fleet.attainment_over(clean["completions"],
                                       restore)
    tail_faulted = fleet.attainment_over(faulted["completions"],
                                         restore)
    tokens = lambda rep: sum(e["tokens"] for e in rep["completions"])  # noqa: E731
    recovered = (tail_clean is None or tail_faulted is None
                 or tail_faulted >= tail_clean)
    sched_counts = faulted["scheduler"]["event_counts"]
    return {
        "plan": plan.as_dict(),
        "requests": len(trace),
        "drain_at_s": at,
        "restore_at_s": restore,
        "sched_events": sched_counts,
        "requeues": faulted["router"]["requeues"],
        "tail_attainment_clean": tail_clean,
        "tail_attainment_faulted": tail_faulted,
        "ok": bool(faulted["ok"] and clean["ok"]
                   and tokens(faulted) == tokens(clean)
                   and sched_counts.get("NodeDrained", 0) == 1
                   and recovered),
    }


@_scenario("sched-preemption-priority",
           "a full cluster meets a high-priority gang: the "
           "scheduler evicts strictly-lower-priority victims "
           "(never equals), reschedules them when capacity frees, "
           "and the seeded event log replays byte-identically")
def _scenario_sched_preemption(seed: int) -> dict:
    import json as _json

    from kind_tpu_sim import sched as sched_mod

    plan = ChaosSchedule(seed).plan(kinds=("node_fail",),
                                    n_faults=1, horizon=8, targets=4)
    ev = plan.events[0]
    # one v5e 4x8 pod: 4 hosts. Fill with 4 low-priority single-host
    # batch gangs that release in a few virtual seconds, then land a
    # high-priority 2-host slice on the full cluster.
    def run():
        inv = sched_mod.build_inventory(
            [("tpu-v5-lite-podslice", "4x8")])
        sched = sched_mod.ClusterScheduler(
            inv, sched_mod.SchedConfig(policy="ici"))
        for i in range(4):
            # hold times vary with the seed so different soak draws
            # exercise different release orders
            sched.submit(sched_mod.SliceRequest(
                name=f"batch-{i}", topology="2x4", priority=-10,
                hold_s=round(3.0 + ((seed >> i) + i) % 4, 6)),
                0.0)
        sched.step(0.0)
        sched.submit(sched_mod.SliceRequest(
            name="serving-hi", topology="4x4", priority=10), 1.0)
        sched.step(1.0)
        # batch victims rescheduled as their preemptor's capacity
        # frees (hold expiry releases both tiers over time)
        now = 1.0
        while (sched.pending or any(
                g.release_s is not None
                for g in sched.bound.values())):
            now = round(now + 0.5, 6)
            if now > 60.0:
                break
            sched.step(now)
        return sched

    s1 = run()
    s2 = run()
    evicted = [e for e in s1.events if e["type"] == "Preempted"]
    hi_bound = [e for e in s1.events
                if e["type"] == "Scheduled"
                and e["gang"] == "serving-hi"]
    sched_counts: Dict[str, int] = {}
    for e in s1.events:
        if e["type"] == "Scheduled":
            sched_counts[e["gang"]] = (
                sched_counts.get(e["gang"], 0) + 1)
    victims = {e["gang"] for e in evicted}
    # a victim was RE-scheduled iff it has a second Scheduled event
    batch_resched = {g for g, n in sched_counts.items()
                     if g.startswith("batch") and n >= 2}
    # strictly-by-priority invariant: only priority -10 batch gangs
    # may ever be displaced by the priority-10 preemptor
    strict = all(g.startswith("batch-") for g in victims)
    identical = (_json.dumps(s1.events, sort_keys=True)
                 == _json.dumps(s2.events, sort_keys=True))
    metrics.recovery_log().record(
        "sched_preemption_scenario", victims=len(victims),
        fault_target=ev.target)
    return {
        "plan": plan.as_dict(),
        "evictions": len(evicted),
        "victims": sorted(victims),
        "high_priority_bound": bool(hi_bound),
        "victims_rescheduled": sorted(
            batch_resched & victims),
        "events_identical": identical,
        "ok": bool(hi_bound and evicted and strict and identical
                   and victims <= batch_resched),
    }


def derive_straggler_bounds(clean_a_s: float, clean_b_s: float,
                            stall_s: float) -> Dict[str, float]:
    """Calibration-derived makespan bounds for the straggler-grid
    scenario (the PR 8 flake fix): the old fixed ratios (on <=
    1.25x clean, off >= 1.3x clean) were judged against ONE clean
    run of REAL subprocesses, so any host-load spike during either
    run flipped the verdict. Two clean runs form a calibration
    probe: their spread measures this host's current wall-clock
    noise, and the injected stall is an ABSOLUTE quantity the
    off-run must serialize at least once while detection keeps the
    on-run from paying more than a detection-window's worth of it —
    additive stall terms, unlike ratios, cannot be forged or erased
    by uniform host slowdown.

    * ``on_limit_s`` — detection-on must finish under 1.25x the
      SLOWER calibration run plus most of one stall (the detector
      eats at most ~a window of stalled work before quarantining).
    * ``off_floor_s`` — detection-off must exceed the FASTER
      calibration run plus over half a stall (the unmitigated
      straggler provably serializes stalled work into the
      makespan)."""
    calib_hi = max(clean_a_s, clean_b_s)
    calib_lo = max(1e-9, min(clean_a_s, clean_b_s))
    return {
        "calib_hi_s": calib_hi,
        "calib_lo_s": calib_lo,
        "on_limit_s": 1.25 * calib_hi + 0.9 * stall_s,
        "off_floor_s": calib_lo + 0.6 * stall_s,
    }


@_scenario("gray-straggler-grid",
           "a gray straggler worker (alive but slow) is probed out "
           "and quarantined; the grid rebalances and its wall "
           "recovers to within a calibration-derived tolerance of "
           "fault-free, results bit-identical — detection-off "
           "provably does not recover")
def _scenario_gray_straggler_grid(seed: int) -> dict:
    import dataclasses as _dc

    from kind_tpu_sim import health
    from kind_tpu_sim.parallel import multihost

    plan = ChaosSchedule(seed).plan(kinds=("straggler_worker",),
                                    n_faults=1, horizon=8, targets=6)
    ev = plan.events[0]
    workers = 6
    stall = min(2.4, max(1.6, ev.param))
    cells = [{"cell": i, "payload": seed % 997, "sleep_s": 0.08}
             for i in range(36)]
    hcfg = _dc.replace(health.DetectorConfig.from_env(),
                       probe_timeout_s=0.8)
    # TWO clean runs: the calibration probe the thresholds derive
    # from (their spread is this host's live wall-clock noise)
    clean, clean_stats = multihost.scatter_grid_cells(
        cells, workers=workers, timeout=180.0,
        detect=True, health_cfg=hcfg)
    clean2, clean2_stats = multihost.scatter_grid_cells(
        cells, workers=workers, timeout=180.0,
        detect=True, health_cfg=hcfg)
    bounds = derive_straggler_bounds(
        clean_stats["makespan_s"], clean2_stats["makespan_s"],
        stall)
    fault = ("straggler", ev.target % workers, stall)
    on, on_stats = multihost.scatter_grid_cells(
        cells, workers=workers, timeout=180.0,
        detect=True, health_cfg=hcfg, fault=fault, max_respawns=1)
    off, off_stats = multihost.scatter_grid_cells(
        cells, workers=workers, timeout=240.0,
        fault=fault, max_respawns=0)
    detected = (on_stats["quarantines"]
                + on_stats["speculative"]) >= 1
    # only the hard transitions go in the report: the shape stays
    # byte-stable across replays (no wall-clock values)
    detection = [d for d in on_stats.get("detection", [])
                 if d["transition"] in ("quarantined", "restored")]
    recovered = on_stats["makespan_s"] <= bounds["on_limit_s"]
    off_degraded = off_stats["makespan_s"] >= bounds["off_floor_s"]
    return {
        "plan": plan.as_dict(),
        "workers": workers,
        "cells": len(cells),
        "faulted_worker": ev.target % workers,
        "results_identical": bool(on == clean and off == clean
                                  and clean2 == clean),
        "fault_free_quarantines": clean_stats["quarantines"]
        + clean2_stats["quarantines"],
        "detected": bool(detected),
        "detection": detection,
        "recovered_within_tolerance": bool(recovered),
        "off_degraded": bool(off_degraded),
        "ok": bool(on == clean and off == clean and clean2 == clean
                   and clean_stats["quarantines"] == 0
                   and clean2_stats["quarantines"] == 0
                   and detected
                   and recovered and off_degraded),
    }


def _window_p99_ttft(completions, t_from: float,
                     t_to: float) -> Optional[float]:
    """p99 TTFT over requests ARRIVING in [t_from, t_to) — the
    post-detection recovery window the gray fleet scenarios are
    judged over."""
    from kind_tpu_sim.fleet.slo import brute_force_percentile

    vals = [(e["first_s"] if e["first_s"] is not None
             else e["finish_s"]) - e["arrival_s"]
            for e in completions
            if t_from <= e["arrival_s"] < t_to]
    return brute_force_percentile(vals, 0.99)


@_scenario("gray-slow-replica",
           "one fleet replica silently slows under seeded traffic; "
           "the detector quarantines it, the router routes around, "
           "probes restore it after the fault lifts, and windowed "
           "p99 TTFT recovers to within tolerance of fault-free — "
           "detection-off provably does not")
def _scenario_gray_slow_replica(seed: int) -> dict:
    import json as _json

    from kind_tpu_sim import fleet, health

    plan = ChaosSchedule(seed).plan(kinds=("slow_replica",),
                                    n_faults=1, horizon=8, targets=3)
    ev = plan.events[0]
    target = ev.target % 3
    factor = max(3.0, ev.param)
    spec = fleet.WorkloadSpec(process="poisson", rps=60.0,
                              n_requests=500, prompt_len=(8, 24),
                              max_new=(4, 12))
    trace = fleet.generate_trace(spec, seed)
    span = max(r.arrival_s for r in trace)
    t1, t2 = round(span * 0.25, 6), round(span * 0.65, 6)
    sim_cfg = fleet.SimReplicaConfig(max_slots=4,
                                     prefill_per_tok_s=0.002,
                                     tpot_s=0.002)
    events = [fleet.ChaosEvent(at_s=t1, action="slow",
                               target=target, param=factor),
              fleet.ChaosEvent(at_s=t2, action="unslow",
                               target=target)]
    hcfg = health.DetectorConfig.from_env()

    def run(detect: bool, ev_list):
        fc = fleet.FleetConfig(
            replicas=3, policy="least-outstanding", tick_s=0.01,
            sim=sim_cfg, slo=fleet.SloPolicy(ttft_s=1.0, e2e_s=5.0),
            health=(hcfg if detect else None))
        return fleet.FleetSim(fc, trace,
                              chaos_events=list(ev_list)).run()

    clean = run(True, [])
    on = run(True, events)
    replay = run(True, events)
    off = run(False, events)
    counters = on["health"]["counters"]
    q_events = [e for e in on["health"]["detector"]["events"]
                if e["transition"] == "quarantined"]
    t_q = q_events[0]["at_s"] if q_events else t1 + 0.5
    p99_clean = _window_p99_ttft(clean["completions"], t_q, t2)
    p99_on = _window_p99_ttft(on["completions"], t_q, t2)
    p99_off = _window_p99_ttft(off["completions"], t_q, t2)
    tokens = lambda rep: sum(e["tokens"]  # noqa: E731
                             for e in rep["completions"])
    recovered = (p99_clean is not None and p99_on is not None
                 and p99_on <= 1.25 * p99_clean)
    off_degraded = (p99_clean is not None and p99_off is not None
                    and p99_off > 1.25 * p99_clean)
    identical = (_json.dumps(on["completions"], sort_keys=True)
                 == _json.dumps(replay["completions"],
                                sort_keys=True)
                 and _json.dumps(on["health"]["detector"]["events"],
                                 sort_keys=True)
                 == _json.dumps(
                     replay["health"]["detector"]["events"],
                     sort_keys=True))
    restored = any(e["transition"] == "restored"
                   and e["component"] == f"replica-{target}"
                   for e in on["health"]["detector"]["events"])
    return {
        "plan": plan.as_dict(),
        "requests": len(trace),
        "slow_replica": target,
        "factor": round(factor, 3),
        "fault_free_quarantines":
            clean["health"]["counters"].get("quarantines", 0),
        "quarantines": counters.get("quarantines", 0),
        "false_positives": counters.get("false_positives", 0),
        "restored_via_probes": bool(restored),
        "p99_recovered": bool(recovered),
        "p99_off_degraded": bool(off_degraded),
        "replay_identical": bool(identical),
        "ok": bool(clean["ok"] and on["ok"] and off["ok"]
                   and clean["health"]["counters"].get(
                       "quarantines", 0) == 0
                   and counters.get("quarantines", 0) >= 1
                   and counters.get("false_positives", 0) == 0
                   and restored
                   and tokens(on) == tokens(clean) == tokens(off)
                   and recovered and off_degraded and identical),
    }


@_scenario("gray-degraded-ici",
           "an ICI link degrades under a scheduler-backed fleet: "
           "the replicas on that domain are quarantined and their "
           "gangs migrate (one at a time) onto the healthy domain, "
           "the scheduler scores the degraded domain last, and "
           "windowed p99 TTFT recovers to fault-free levels — "
           "detection-off stays degraded until the link heals")
def _scenario_gray_degraded_ici(seed: int) -> dict:
    import json as _json

    from kind_tpu_sim import fleet, health

    plan = ChaosSchedule(seed).plan(kinds=("degraded_link",),
                                    n_faults=1, horizon=8, targets=2)
    ev = plan.events[0]
    factor = min(0.25, max(0.08, ev.param))
    spec = fleet.WorkloadSpec(process="poisson", rps=60.0,
                              n_requests=500, prompt_len=(8, 24),
                              max_new=(4, 12))
    trace = fleet.generate_trace(spec, seed)
    span = max(r.arrival_s for r in trace)
    t1, t2 = round(span * 0.25, 6), round(span * 0.7, 6)
    sim_cfg = fleet.SimReplicaConfig(max_slots=4,
                                     prefill_per_tok_s=0.002,
                                     tpot_s=0.002)
    # spread placement: one replica per ICI domain, so degrading one
    # domain grays out ONE replica — ici/binpack would co-locate both
    # gangs and a single bad link would migrate the whole fleet
    sc = fleet.FleetSchedConfig(
        pods=(("tpu-v5-lite-podslice", "4x8"),
              ("tpu-v5-lite-podslice", "4x8")),
        policy="spread")
    hcfg = health.DetectorConfig.from_env()

    def run(detect: bool, ev_list):
        fc = fleet.FleetConfig(
            replicas=2, policy="least-outstanding", tick_s=0.01,
            sim=sim_cfg, slo=fleet.SloPolicy(ttft_s=1.0, e2e_s=5.0),
            sched=sc, health=(hcfg if detect else None))
        return fleet.FleetSim(fc, trace,
                              chaos_events=list(ev_list)).run()

    clean = run(True, [])
    # degrade the domain that PROVABLY hosts a replica gang (the
    # runs are identical up to the degrade instant, so the clean
    # run's t=0 placement names the victim domain)
    placed = next(
        e for e in clean["scheduler"]["events"]
        if e["type"] == "Scheduled"
        and e["gang"] == f"replica-{ev.target % 2}")
    victim_domain = int(placed["nodes"][0].split("-")[2])
    events = [fleet.ChaosEvent(at_s=t1, action="link_degrade",
                               target=victim_domain, param=factor),
              fleet.ChaosEvent(at_s=t2, action="link_restore",
                               target=victim_domain)]
    on = run(True, events)
    replay = run(True, events)
    off = run(False, events)
    counters = on["health"]["counters"]
    sched_counts = on["scheduler"]["event_counts"]
    restored_events = [
        e for e in on["health"]["detector"]["events"]
        if e["transition"] == "restored"]
    ready = (max(e["at_s"] for e in restored_events) + 0.3
             if restored_events else t1 + 1.0)
    p99_clean = _window_p99_ttft(clean["completions"], ready, t2)
    p99_on = _window_p99_ttft(on["completions"], ready, t2)
    p99_off = _window_p99_ttft(off["completions"], ready, t2)
    # every post-migration Scheduled event must land OFF the
    # degraded domain (the scoring + avoid-mark contract)
    migrated_clean = all(
        int(e["nodes"][0].split("-")[2]) != victim_domain
        for e in on["scheduler"]["events"]
        if e["type"] == "Scheduled" and e["at_s"] > t1)
    tokens = lambda rep: sum(e["tokens"]  # noqa: E731
                             for e in rep["completions"])
    recovered = (p99_clean is not None and p99_on is not None
                 and p99_on <= 1.25 * p99_clean)
    off_degraded = (p99_clean is not None and p99_off is not None
                    and p99_off > 1.25 * p99_clean)
    identical = (
        _json.dumps(on["completions"], sort_keys=True)
        == _json.dumps(replay["completions"], sort_keys=True)
        and _json.dumps(on["scheduler"]["events"], sort_keys=True)
        == _json.dumps(replay["scheduler"]["events"],
                       sort_keys=True)
        and _json.dumps(on["health"]["detector"]["events"],
                        sort_keys=True)
        == _json.dumps(replay["health"]["detector"]["events"],
                       sort_keys=True))
    return {
        "plan": plan.as_dict(),
        "requests": len(trace),
        "degraded_domain": victim_domain,
        "link_factor": round(factor, 3),
        "fault_free_quarantines":
            clean["health"]["counters"].get("quarantines", 0),
        "quarantines": counters.get("quarantines", 0),
        "false_positives": counters.get("false_positives", 0),
        "gray_migrations": counters.get("gray_migrations", 0),
        "link_events": {
            "degraded": sched_counts.get("LinkDegraded", 0),
            "restored": sched_counts.get("LinkRestored", 0)},
        "migrations_avoid_degraded_domain": bool(migrated_clean),
        "p99_recovered": bool(recovered),
        "p99_off_degraded": bool(off_degraded),
        "replay_identical": bool(identical),
        "ok": bool(clean["ok"] and on["ok"] and off["ok"]
                   and clean["health"]["counters"].get(
                       "quarantines", 0) == 0
                   and counters.get("quarantines", 0) >= 1
                   and counters.get("false_positives", 0) == 0
                   and counters.get("gray_migrations", 0) >= 1
                   and sched_counts.get("LinkDegraded", 0) == 1
                   and migrated_clean
                   and tokens(on) == tokens(clean) == tokens(off)
                   and recovered and off_degraded and identical),
    }


@_scenario("globe-zone-loss",
           "a whole MULTI-CELL zone goes dark under the globe's "
           "front door: both of its cells' load spills cross-zone "
           "(nearest healthy first), zero requests are lost, global "
           "p99 recovers after the zone returns, and the surviving "
           "zones' boards stay within noise of fault-free — the "
           "blast radius is the zone, not the planet")
def _scenario_globe_zone_loss(seed: int) -> dict:
    import json as _json

    from kind_tpu_sim import globe

    plan = ChaosSchedule(seed).plan(kinds=("zone_loss",),
                                    n_faults=1, horizon=6, targets=3)
    ev = plan.events[0]
    # 2 cells per zone: the blast radius spans BOTH cells of the
    # lost zone (they die together — a zone is a correlated failure
    # domain) and the herd readmission spreads over the survivors'
    # four cells, sibling-first within each zone
    cfg = globe.GlobeConfig(
        zones=("zone-a", "zone-b", "zone-c"), cells_per_zone=2,
        replicas_per_cell=1,
        workload=globe.GlobeWorkloadSpec(process="poisson",
                                         rps=30.0, n_per_zone=120))
    traces = globe.generate_globe_traces(cfg, seed)
    span = max(r.arrival_s for reqs in traces.values()
               for r in reqs)
    lost_zone = cfg.zones[ev.target % len(cfg.zones)]
    # the loss lands a third into the arrival window and the zone
    # returns at two thirds — a full third of the trace arrives
    # post-restore, so the recovery window has real traffic to judge
    at = round(span / 3.0, 6)
    restore = round(2.0 * span / 3.0, 6)
    events = [
        globe.GlobeChaosEvent(at_s=at, action="zone_loss",
                              target=lost_zone),
        globe.GlobeChaosEvent(at_s=restore, action="zone_restore",
                              target=lost_zone),
    ]
    clean = globe.GlobeSim(cfg, traces=traces, seed=seed).run()
    faulted = globe.GlobeSim(cfg, traces=traces, seed=seed,
                             chaos_events=events).run()
    replay = globe.GlobeSim(cfg, traces=traces, seed=seed,
                            chaos_events=events).run()
    # recovery: post-restore global p99 back to fault-free levels
    p99_clean = _window_p99_ttft(clean["completions"], restore,
                                 span + 1.0)
    p99_faulted = _window_p99_ttft(faulted["completions"], restore,
                                   span + 1.0)
    recovered = (p99_clean is not None and p99_faulted is not None
                 and p99_faulted <= 1.25 * p99_clean)
    # containment: the surviving zones' per-zone boards (whole run,
    # fault window included) must sit within noise of fault-free —
    # a zone loss that degrades its neighbors was not contained.
    # One histogram bucket is 1.12x; survivors legitimately carry
    # the herd's spill DURING the window (they absorb 1.5x load by
    # design), so their tolerance is ~3 buckets (1.12^3 = 1.405) —
    # soak seeds land ratios up to ~1.35 with the spill fully
    # bounded. Post-restore RECOVERY keeps the tighter 1.25.
    survivors = [z for z in cfg.zones if z != lost_zone]
    containment = {}
    for z in survivors:
        pc = clean["zones"][z]["slo"]["ttft"].get("p99_s")
        pf = faulted["zones"][z]["slo"]["ttft"].get("p99_s")
        containment[z] = (round(pf / pc, 3)
                          if pc and pf is not None else None)
    contained = all(r is not None and r <= 1.405
                    for r in containment.values())
    tokens = lambda rep: sum(e["tokens"] for e in rep["completions"])  # noqa: E731
    identical = (_json.dumps(faulted["completions"],
                             sort_keys=True)
                 == _json.dumps(replay["completions"],
                                sort_keys=True))
    return {
        "plan": plan.as_dict(),
        "requests": faulted["requests"],
        "lost_zone": lost_zone,
        "loss_at_s": at,
        "restore_at_s": restore,
        "spilled": faulted["frontdoor"]["spilled"],
        "readmitted": faulted["frontdoor"]["readmitted"],
        "shed": faulted["global_slo"]["shed"],
        "p99_post_restore_ratio": (
            round(p99_faulted / p99_clean, 3)
            if p99_clean and p99_faulted is not None else None),
        "surviving_zone_p99_ratio": containment,
        "replay_identical": bool(identical),
        "ok": bool(clean["ok"] and faulted["ok"]
                   and faulted["global_slo"]["shed"] == 0
                   and tokens(faulted) == tokens(clean)
                   and faulted["frontdoor"]["spilled"] >= 1
                   and recovered and contained and identical),
    }


@_scenario("globe-herd-failover",
           "a zone dies at peak burst: its whole load hits the "
           "front door at once, and the spill bound spreads it "
           "without cascade — no surviving cell is flooded past its "
           "configured headroom, nothing sheds, and attainment "
           "recovers once the zone returns")
def _scenario_globe_herd_failover(seed: int) -> dict:
    import json as _json

    from kind_tpu_sim import globe

    plan = ChaosSchedule(seed).plan(kinds=("herd_failover",),
                                    n_faults=1, horizon=6, targets=3)
    ev = plan.events[0]
    cfg = globe.GlobeConfig(
        zones=("zone-a", "zone-b", "zone-c"), replicas_per_cell=2,
        workload=globe.GlobeWorkloadSpec(process="bursty",
                                         rps=40.0, n_per_zone=150))
    traces = globe.generate_globe_traces(cfg, seed)
    span = max(r.arrival_s for reqs in traces.values()
               for r in reqs)
    herd_zone = cfg.zones[ev.target % len(cfg.zones)]
    clean = globe.GlobeSim(cfg, traces=traces, seed=seed).run()
    # kill the zone just after a mid-trace dispatch INTO it: the
    # runs are identical up to that instant, so the zone provably
    # holds in-flight work — the herd (displacement + re-admission)
    # is guaranteed, not seed-lucky (burst valleys are empty)
    tick = 0.01
    herd_disp = sorted(
        e["dispatch_s"] for e in clean["completions"]
        if e["serving_zone"] == herd_zone
        and span / 4.0 <= e["dispatch_s"] <= 2.0 * span / 3.0)
    at = round((herd_disp[len(herd_disp) // 2] + tick / 2
                if herd_disp else span / 3.0), 6)
    restore = round(max(2.0 * span / 3.0, at + 0.15 * span), 6)
    events = [
        globe.GlobeChaosEvent(at_s=at, action="herd_failover",
                              target=herd_zone),
        globe.GlobeChaosEvent(at_s=restore, action="zone_restore",
                              target=herd_zone),
    ]
    faulted = globe.GlobeSim(cfg, traces=traces, seed=seed,
                             chaos_events=events).run()
    replay = globe.GlobeSim(cfg, traces=traces, seed=seed,
                            chaos_events=events).run()
    fd = faulted["frontdoor"]
    # the cascade-prevention invariant: admission never floods any
    # surviving cell past its hard limit (nominal x (1 + headroom)),
    # and neither tier sheds — overflow waits at the front door
    bounded = all(
        fd["peak_outstanding"][name] <= fd["hard_limits"][name]
        for name in fd["hard_limits"])
    cell_sheds = sum(c["router"]["shed"]
                     for c in faulted["cells"].values())
    tail_clean = globe.attainment_over(clean["completions"],
                                       restore)
    tail_faulted = globe.attainment_over(faulted["completions"],
                                         restore)
    recovered = (tail_clean is None or tail_faulted is None
                 or tail_faulted >= tail_clean)
    tokens = lambda rep: sum(e["tokens"] for e in rep["completions"])  # noqa: E731
    identical = (_json.dumps(faulted["completions"],
                             sort_keys=True)
                 == _json.dumps(replay["completions"],
                                sort_keys=True))
    return {
        "plan": plan.as_dict(),
        "requests": faulted["requests"],
        "herd_zone": herd_zone,
        "failover_at_s": at,
        "readmitted": fd["readmitted"],
        "spilled": fd["spilled"],
        "peak_outstanding": fd["peak_outstanding"],
        "hard_limits": fd["hard_limits"],
        "spill_bound_held": bool(bounded),
        "cell_sheds": cell_sheds,
        "frontdoor_sheds": fd["shed"],
        "tail_attainment_clean": tail_clean,
        "tail_attainment_faulted": tail_faulted,
        "replay_identical": bool(identical),
        "ok": bool(clean["ok"] and faulted["ok"]
                   and bounded and cell_sheds == 0
                   and fd["shed"] == 0
                   and fd["readmitted"] >= 1
                   and tokens(faulted) == tokens(clean)
                   and recovered and identical),
    }


@_scenario("globe-dcn-degrade",
           "an inter-zone DCN link browns out under cross-zone "
           "spill (one cell drained for maintenance forces the "
           "spill): the latency-aware front door routes around the "
           "degraded path, the untouched zone's board stays within "
           "noise, and the spill path heals when the link does")
def _scenario_globe_dcn_degrade(seed: int) -> dict:
    import json as _json

    from kind_tpu_sim import globe

    plan = ChaosSchedule(seed).plan(kinds=("dcn_degrade",),
                                    n_faults=1, horizon=8, targets=1)
    factor = min(0.25, max(0.08, plan.events[0].param))
    # 3 replicas/cell: zone-a must absorb zone-b's whole spill
    # WITHOUT ever saturating — a saturated near cell would let the
    # front door legitimately prefer the far (degraded) path, and
    # this scenario is about latency steering, not overload
    cfg = globe.GlobeConfig(
        zones=("zone-a", "zone-b", "zone-c"), replicas_per_cell=3,
        workload=globe.GlobeWorkloadSpec(process="poisson",
                                         rps=30.0, n_per_zone=120))
    traces = globe.generate_globe_traces(cfg, seed)
    span = max(r.arrival_s for reqs in traces.values()
               for r in reqs)
    t1 = round(span * 0.25, 6)
    t2 = round(span * 0.65, 6)
    # zone-b's cell is under maintenance the whole run (baseline
    # includes the drain, so the faulted-vs-baseline delta is PURELY
    # the browned-out link); its traffic must spill cross-zone,
    # where zone-a and zone-c are equidistant candidates
    drain = [globe.GlobeChaosEvent(at_s=0.0, action="cell_drain",
                                   target="zone-b/c0")]
    dcn = drain + [
        globe.GlobeChaosEvent(at_s=t1, action="dcn_degrade",
                              target="zone-c", param=factor),
        globe.GlobeChaosEvent(at_s=t2, action="dcn_restore",
                              target="zone-c"),
    ]
    base = globe.GlobeSim(cfg, traces=traces, seed=seed,
                          chaos_events=list(drain)).run()
    faulted = globe.GlobeSim(cfg, traces=traces, seed=seed,
                             chaos_events=list(dcn)).run()
    replay = globe.GlobeSim(cfg, traces=traces, seed=seed,
                            chaos_events=list(dcn)).run()
    # steering: while zone-c's DCN is browned out, NO zone-b
    # request may be served through it — the front door's
    # latency-aware scoring must prefer the healthy path to zone-a.
    # The window edges back off a tick: an arrival 1ms before the
    # restore is ADMITTED at the next tick, after the link healed
    window = [e for e in faulted["completions"]
              if e["origin"] == "zone-b"
              and t1 + 0.1 <= e["arrival_s"] < t2 - 0.05]
    routed_around = (all(e["serving_zone"] != "zone-c"
                         for e in window)
                     and any(e["serving_zone"] == "zone-a"
                             for e in window))
    # containment: zone-c's own (purely local) traffic must not
    # notice its DCN links browning out (1.25 = ~2 histogram
    # buckets, the repo-wide fault-free tolerance)
    pc = base["zones"]["zone-c"]["slo"]["ttft"].get("p99_s")
    pf = faulted["zones"]["zone-c"]["slo"]["ttft"].get("p99_s")
    contained = bool(pc and pf is not None and pf <= 1.25 * pc)
    # recovery: once the link heals, the spill path costs what it
    # did under maintenance alone
    p99_base = _window_p99_ttft(base["completions"], t2, span + 1.0)
    p99_faulted = _window_p99_ttft(faulted["completions"], t2,
                                   span + 1.0)
    recovered = (p99_base is not None and p99_faulted is not None
                 and p99_faulted <= 1.25 * p99_base)
    tokens = lambda rep: sum(e["tokens"] for e in rep["completions"])  # noqa: E731
    identical = (_json.dumps(faulted["completions"],
                             sort_keys=True)
                 == _json.dumps(replay["completions"],
                                sort_keys=True))
    return {
        "plan": plan.as_dict(),
        "requests": faulted["requests"],
        "link_factor": round(factor, 3),
        "degrade_window_s": [t1, t2],
        "spill_window_requests": len(window),
        "routed_around_degraded_link": bool(routed_around),
        "zone_c_p99_ratio": (round(pf / pc, 3)
                             if pc and pf is not None else None),
        "p99_post_restore_ratio": (
            round(p99_faulted / p99_base, 3)
            if p99_base and p99_faulted is not None else None),
        "dcn_degrades": faulted["globe_counters"].get(
            "dcn_degrades", 0),
        "replay_identical": bool(identical),
        "ok": bool(base["ok"] and faulted["ok"]
                   and len(window) >= 5
                   and routed_around and contained and recovered
                   and faulted["globe_counters"].get(
                       "dcn_degrades", 0) == 1
                   and tokens(faulted) == tokens(base)
                   and identical),
    }


def _overload_window_stats(completions, t_from: float,
                           t_to: float) -> dict:
    """Windowed observables the overload scenarios are judged on:
    p99 TTFT over arrivals in the window plus attained-goodput
    (tokens of SLO-attained requests per second of window)."""
    toks = sum(e["tokens"] for e in completions
               if t_from <= e["arrival_s"] < t_to and e["slo_ok"])
    return {
        "p99_ttft_s": _window_p99_ttft(completions, t_from, t_to),
        "goodput_tok_s": round(toks / max(1e-9, t_to - t_from), 3),
    }


@_scenario("overload-surge",
           "a seeded demand surge (step multiplier on arrivals) "
           "saturates the fleet: retry budgets, hedging bounds, "
           "breakers, and brownout keep goodput above the floor and "
           "p99 recovers to fault-free once the surge clears, while "
           "a controls-off client provably enters sustained "
           "metastable collapse — load returns to normal, latency "
           "does not")
def _scenario_overload_surge(seed: int) -> dict:
    import json as _json

    from kind_tpu_sim import fleet

    plan = ChaosSchedule(seed).plan(kinds=("demand_surge",),
                                    n_faults=1, horizon=8, targets=1)
    mult = min(5.0, max(3.0, plan.events[0].param))
    # ~72% base utilization (3 replicas x 4 slots at ~17 req/s per
    # slot): healthy headroom fault-free, saturated x3-x5 under the
    # surge; the tight deadline makes saturation produce the misses
    # a storm feeds on
    spec = fleet.WorkloadSpec(process="poisson", rps=150.0,
                              n_requests=900, prompt_len=(8, 24),
                              max_new=(4, 12), deadline_s=0.6)
    base = fleet.generate_trace(spec, seed)
    span = max(r.arrival_s for r in base)
    t0, t1 = round(span * 0.3, 6), round(span * 0.45, 6)
    surge = fleet.surge_trace(spec, seed, t0, t1, mult)
    sim_cfg = fleet.SimReplicaConfig(max_slots=4,
                                     prefill_per_tok_s=0.002,
                                     tpot_s=0.002)
    slo = fleet.SloPolicy(ttft_s=0.3, e2e_s=0.6)

    def run(trace, ov):
        fc = fleet.FleetConfig(replicas=3,
                               policy="least-outstanding",
                               tick_s=0.01, sim=sim_cfg, slo=slo,
                               max_queue=512, overload=ov,
                               max_virtual_s=60.0)
        return fleet.FleetSim(fc, trace).run()

    clean = run(base, fleet.OverloadConfig())
    on = run(surge, fleet.OverloadConfig())
    replay = run(surge, fleet.OverloadConfig())
    off = run(surge, fleet.OverloadConfig.uncontrolled(
        max_attempts=6))
    # the judged windows: goodput floor DURING the surge, p99
    # recovery well after the trigger cleared (arrivals only — the
    # backlog-drain period must not pollute the recovery verdict)
    w0, w1 = round(t1 + 2.0, 6), round(span - 0.2, 6)
    surge_clean = _overload_window_stats(clean["completions"],
                                         t0, t1)
    surge_on = _overload_window_stats(on["completions"], t0, t1)
    rec_clean = _overload_window_stats(clean["completions"], w0, w1)
    rec_on = _overload_window_stats(on["completions"], w0, w1)
    rec_off = _overload_window_stats(off["completions"], w0, w1)
    goodput_floor = 0.4  # fraction of fault-free surge-window goodput
    floor_held = (surge_on["goodput_tok_s"]
                  >= goodput_floor * surge_clean["goodput_tok_s"])
    p_c = rec_clean["p99_ttft_s"]
    p_on = rec_on["p99_ttft_s"]
    p_off = rec_off["p99_ttft_s"]
    recovered = (p_c is not None and p_on is not None
                 and p_on <= 1.25 * p_c)
    # the metastable signature: arrivals are back at the base rate
    # in the judged window, yet the controls-off fleet still serves
    # them collapsed
    off_collapsed = (p_c is not None and p_off is not None
                     and p_off > 1.25 * p_c)
    oc_on = on["overload"]["counters"]
    oc_off = off["overload"]["counters"]
    identical = (_json.dumps(on["completions"], sort_keys=True)
                 == _json.dumps(replay["completions"],
                                sort_keys=True)
                 and _json.dumps(on["overload"], sort_keys=True)
                 == _json.dumps(replay["overload"], sort_keys=True))
    return {
        "plan": plan.as_dict(),
        "requests": len(surge),
        "surge_multiplier": round(mult, 3),
        "surge_window_s": [t0, t1],
        "recovery_window_s": [w0, w1],
        "goodput_floor_frac": goodput_floor,
        "surge_goodput_clean": surge_clean["goodput_tok_s"],
        "surge_goodput_on": surge_on["goodput_tok_s"],
        "goodput_floor_held": bool(floor_held),
        "p99_recovery_ratio_on": (round(p_on / p_c, 3)
                                  if p_c and p_on is not None
                                  else None),
        "p99_recovery_ratio_off": (round(p_off / p_c, 3)
                                   if p_c and p_off is not None
                                   else None),
        "retries_suppressed": oc_on.get("retries_suppressed", 0),
        "retries_on": oc_on.get("retries_scheduled", 0),
        "retries_off": oc_off.get("retries_scheduled", 0),
        "hedges_issued": oc_on.get("hedges_issued", 0),
        "hedges_suppressed": oc_on.get("hedges_suppressed", 0),
        "brownout": on["overload"]["brownout"]["transitions"],
        "replay_identical": bool(identical),
        "ok": bool(clean["ok"] and on["ok"] and off["ok"]
                   and floor_held and recovered and off_collapsed
                   and oc_on.get("retries_suppressed", 0) >= 1
                   and oc_off.get("retries_scheduled", 0)
                   > oc_on.get("retries_scheduled", 0)
                   and identical),
    }


@_scenario("retry-storm",
           "a transient replica outage under seeded traffic turns "
           "client retries into a storm: the token-bucket retry "
           "budget suppresses the amplification (suppressed count "
           "proves it) and p99 recovers once the replica heals, "
           "while an unbudgeted client keeps the surviving capacity "
           "saturated long after — the retry-storm flavor of "
           "metastable failure")
def _scenario_retry_storm(seed: int) -> dict:
    import json as _json

    from kind_tpu_sim import fleet

    plan = ChaosSchedule(seed).plan(kinds=("retry_storm",),
                                    n_faults=1, horizon=8, targets=2)
    ev = plan.events[0]
    amplification = int(min(5.0, max(3.0, ev.param)))
    # ~85% utilization on 2 replicas: fault-free holds the SLO, but
    # losing one replica mid-trace halves capacity well below the
    # arrival rate — the kick that starts the storm
    spec = fleet.WorkloadSpec(process="poisson", rps=118.0,
                              n_requests=800, prompt_len=(8, 24),
                              max_new=(4, 12), deadline_s=0.6)
    trace = fleet.generate_trace(spec, seed)
    span = max(r.arrival_s for r in trace)
    t1, t2 = round(span * 0.25, 6), round(span * 0.55, 6)
    target = ev.target % 2
    events = [fleet.ChaosEvent(at_s=t1, action="preempt",
                               target=target),
              fleet.ChaosEvent(at_s=t2, action="restore",
                               target=target)]
    sim_cfg = fleet.SimReplicaConfig(max_slots=4,
                                     prefill_per_tok_s=0.002,
                                     tpot_s=0.002)
    slo = fleet.SloPolicy(ttft_s=0.3, e2e_s=0.6)

    def run(evs, ov):
        fc = fleet.FleetConfig(replicas=2,
                               policy="least-outstanding",
                               tick_s=0.01, sim=sim_cfg, slo=slo,
                               max_queue=512, overload=ov,
                               max_virtual_s=60.0)
        return fleet.FleetSim(fc, trace,
                              chaos_events=list(evs)).run()

    clean = run([], fleet.OverloadConfig())
    on = run(events, fleet.OverloadConfig())
    replay = run(events, fleet.OverloadConfig())
    off = run(events, fleet.OverloadConfig.uncontrolled(
        max_attempts=amplification))
    w0, w1 = round(t2 + 2.0, 6), round(span - 0.2, 6)
    rec_clean = _overload_window_stats(clean["completions"], w0, w1)
    rec_on = _overload_window_stats(on["completions"], w0, w1)
    rec_off = _overload_window_stats(off["completions"], w0, w1)
    p_c = rec_clean["p99_ttft_s"]
    p_on = rec_on["p99_ttft_s"]
    p_off = rec_off["p99_ttft_s"]
    recovered = (p_c is not None and p_on is not None
                 and p_on <= 1.25 * p_c)
    off_collapsed = (p_c is not None and p_off is not None
                     and p_off > 1.25 * p_c)
    oc_on = on["overload"]["counters"]
    oc_off = off["overload"]["counters"]
    identical = (_json.dumps(on["completions"], sort_keys=True)
                 == _json.dumps(replay["completions"],
                                sort_keys=True)
                 and _json.dumps(on["overload"], sort_keys=True)
                 == _json.dumps(replay["overload"], sort_keys=True))
    return {
        "plan": plan.as_dict(),
        "requests": len(trace),
        "amplification": amplification,
        "outage_window_s": [t1, t2],
        "recovery_window_s": [w0, w1],
        "preempted_replica": target,
        "p99_recovery_ratio_on": (round(p_on / p_c, 3)
                                  if p_c and p_on is not None
                                  else None),
        "p99_recovery_ratio_off": (round(p_off / p_c, 3)
                                   if p_c and p_off is not None
                                   else None),
        "retries_suppressed": oc_on.get("retries_suppressed", 0),
        "retries_on": oc_on.get("retries_scheduled", 0),
        "retries_off": oc_off.get("retries_scheduled", 0),
        "requeues": on["router"]["requeues"],
        "replay_identical": bool(identical),
        "ok": bool(clean["ok"] and on["ok"] and off["ok"]
                   and recovered and off_collapsed
                   and oc_on.get("retries_suppressed", 0) >= 1
                   and oc_off.get("retries_scheduled", 0)
                   > oc_on.get("retries_scheduled", 0)
                   and identical),
    }


@_scenario("train-preempt-economics",
           "a training gang under graceful preemption and a hard "
           "kill, run at a tight (Young-Daly) vs loose checkpoint "
           "cadence: graceful preemptions lose zero steps at BOTH "
           "cadences (the PreemptionGuard contract), the hard kill "
           "loses strictly more at the loose cadence while the "
           "tight one pays more write overhead — the economics the "
           "cadence knob trades — and the ledger verifies zero "
           "duplicated steps, byte-identical on replay")
def _scenario_train_preempt_economics(seed: int) -> dict:
    import json as _json

    from kind_tpu_sim import fleet

    plan = ChaosSchedule(seed).plan(
        kinds=("train_preempt", "train_kill"),
        n_faults=2, horizon=8, targets=1)
    spec = fleet.WorkloadSpec(process="poisson", rps=40.0,
                              n_requests=120, prompt_len=(8, 24),
                              max_new=(4, 12))
    trace = fleet.generate_trace(spec, seed)
    sim_cfg = fleet.SimReplicaConfig(max_slots=4,
                                     prefill_per_tok_s=0.002,
                                     tpot_s=0.002)
    sc = fleet.FleetSchedConfig(
        pods=(("tpu-v5-lite-podslice", "4x8"),
              ("tpu-v5-lite-podslice", "4x8")))
    total = 90
    gang = fleet.TrainingGangConfig(name="llm0", total_steps=total)
    step_s = fleet.step_time_s(gang, gang.topology)
    # one graceful preempt early, the hard kill well after it: the
    # kill's rollback distance is then the cadence's to bound
    t_preempt = round(0.5 + 0.1 * plan.events[0].at, 6)
    t_kill = round(t_preempt + 1.2 + 0.05 * plan.events[1].at, 6)
    events = [
        fleet.ChaosEvent(at_s=t_preempt, action="train_preempt",
                         target=0),
        fleet.ChaosEvent(at_s=t_kill, action="train_kill",
                         target=0),
    ]
    write_s = fleet.TrainingConfig().as_dict()[
        "checkpoint_write_s"]
    tight = fleet.optimal_cadence_steps(step_s, write_s,
                                        mtbf_s=1.0)
    loose = total  # only the final checkpoint

    def run(cadence):
        tc = fleet.TrainingConfig(gangs=(dataclasses.replace(
            gang, checkpoint_every=cadence),))
        fc = fleet.FleetConfig(
            replicas=2, policy="least-outstanding", tick_s=0.01,
            sim=sim_cfg, slo=fleet.SloPolicy(ttft_s=1.0, e2e_s=5.0),
            sched=sc, training=tc, max_virtual_s=120.0)
        return fleet.FleetSim(fc, trace,
                              chaos_events=list(events)).run()

    rep_t = run(tight)
    replay = run(tight)
    rep_l = run(loose)
    g_t = rep_t["training"]["gangs"]["llm0"]
    g_l = rep_l["training"]["gangs"]["llm0"]
    eo_t = fleet.expected_overhead(step_s, tight, write_s,
                                   mtbf_s=1.0)
    eo_l = fleet.expected_overhead(step_s, loose, write_s,
                                   mtbf_s=1.0)
    identical = (_json.dumps(rep_t, sort_keys=True)
                 == _json.dumps(replay, sort_keys=True))
    # graceful-preempt evictions lose nothing: every lost step must
    # be attributable to the ONE hard kill (<= one cadence interval
    # at the tight cadence)
    econ = (g_l["lost_steps"] > g_t["lost_steps"]
            and g_t["lost_steps"] <= tight
            and g_t["checkpoint"]["writes"]
            > g_l["checkpoint"]["writes"]
            and eo_t["write_frac"] > eo_l["write_frac"]
            and eo_t["lost_frac"] < eo_l["lost_frac"])
    return {
        "plan": plan.as_dict(),
        "cadences": {"tight": tight, "loose": loose},
        "preempt_at_s": t_preempt,
        "kill_at_s": t_kill,
        "lost_steps": {"tight": g_t["lost_steps"],
                       "loose": g_l["lost_steps"]},
        "checkpoint_writes": {
            "tight": g_t["checkpoint"]["writes"],
            "loose": g_l["checkpoint"]["writes"]},
        "overhead_frac": {"tight": g_t["overhead_frac"],
                          "loose": g_l["overhead_frac"]},
        "expected_overhead": {"tight": eo_t, "loose": eo_l},
        "ledger_ok": bool(g_t["ledger_verify"]["ok"]
                          and g_l["ledger_verify"]["ok"]),
        "economics_hold": bool(econ),
        "replay_identical": bool(identical),
        "ok": bool(rep_t["ok"] and rep_l["ok"]
                   and g_t["state"] == "done"
                   and g_l["state"] == "done"
                   and g_t["ledger_verify"]["ok"]
                   and g_l["ledger_verify"]["ok"]
                   and econ and identical),
    }


@_scenario("train-mixed-soak",
           "serving + LLM training + Ising batch co-scheduled on "
           "one tight inventory under node_drain / node_fail / "
           "replica_preempt chaos: strict priority preempts "
           "training for serving (never the reverse), every gang "
           "finishes with a clean ledger (zero lost, zero "
           "duplicated steps), serving p99 stays within 1.25x of "
           "serving-alone, and the report is byte-identical on "
           "replay AND with the event core off")
def _scenario_train_mixed_soak(seed: int) -> dict:
    import json as _json

    from kind_tpu_sim import fleet

    plan = ChaosSchedule(seed).plan(
        kinds=("node_drain", "replica_preempt", "node_fail"),
        n_faults=3, horizon=9, targets=4)
    spec = fleet.WorkloadSpec(process="poisson", rps=60.0,
                              n_requests=300, prompt_len=(8, 24),
                              max_new=(4, 12))
    trace = fleet.generate_trace(spec, seed)
    span = max(r.arrival_s for r in trace)
    sim_cfg = fleet.SimReplicaConfig(max_slots=4,
                                     prefill_per_tok_s=0.002,
                                     tpot_s=0.002)
    # heterogeneous inventory: serving owns the v5e domain (3
    # whole-host replicas + the Ising batch's chip fragment fill it
    # EXACTLY), training's LLM gang owns a 4-host v4 domain. The
    # accelerator split makes every completion provable — serving
    # can never strand the v4 gang — while the FULL v5e domain
    # forces the strict-priority path: a failed serving node has no
    # free host, so the scheduler must preempt the lowest-priority
    # training tenant (the Ising sweep) to rebind serving
    sc = fleet.FleetSchedConfig(
        pods=(("tpu-v5-lite-podslice", "4x8"),
              ("tpu-v4-podslice", "2x2x4")))
    tc = fleet.TrainingConfig(gangs=(
        fleet.TrainingGangConfig(name="llm0",
                                 accelerator="tpu-v4-podslice",
                                 topology="2x2x4",
                                 total_steps=70,
                                 checkpoint_every=8),
        # long enough that the sweep provably still runs when the
        # node_fail lands at 0.7x the trace span — the sweep IS the
        # strict-priority victim the full domain forces
        fleet.ising_gang("ising0", total_steps=200, priority=-20,
                         checkpoint_every=25),
    ))

    def run(training, event_core=None):
        fc = fleet.FleetConfig(
            replicas=3, policy="least-outstanding", tick_s=0.01,
            sim=sim_cfg, slo=fleet.SloPolicy(ttft_s=1.0, e2e_s=5.0),
            sched=sc, training=(tc if training else None),
            max_virtual_s=120.0, event_core=event_core,
            fast_forward=(False if event_core is False else None))
        return fleet.FleetSim(fc, trace,
                              chaos_events=events).run()

    # the clean mixed run names (a) a node provably hosting the LLM
    # gang (drain it: checkpoint -> evict -> resume on restore) and
    # (b) a node provably hosting a SERVING replica (fail it: the
    # full domain forces preemption of the Ising tenant) —
    # guaranteed displacement, not seed-lucky
    events = []
    clean = run(True)
    node_names = sorted(
        n["name"]
        for d in fleet.FleetSim(
            fleet.FleetConfig(replicas=3, sched=sc),
            []).sched.inv.as_dict()["domains"].values()
        for n in d["nodes"])
    llm_placed = next(
        e for e in clean["scheduler"]["events"]
        if e["type"] == "Scheduled" and e["gang"] == "train-llm0")
    drain_target = node_names.index(
        llm_placed["nodes"][plan.events[0].target
                            % len(llm_placed["nodes"])])
    victim_replica = plan.events[1].target % 3
    srv_placed = next(
        e for e in clean["scheduler"]["events"]
        if e["type"] == "Scheduled"
        and e["gang"] == f"replica-{victim_replica}")
    fail_target = node_names.index(srv_placed["nodes"][0])
    t1 = round(span * 0.2, 6)
    t2 = round(span * 0.45, 6)
    t3 = round(span * 0.55, 6)
    t4 = round(span * 0.7, 6)
    events = [
        fleet.ChaosEvent(at_s=t1, action="node_drain",
                         target=drain_target),
        fleet.ChaosEvent(at_s=t2, action="node_restore",
                         target=drain_target),
        fleet.ChaosEvent(at_s=t3, action="preempt",
                         target=(victim_replica + 1) % 3),
        fleet.ChaosEvent(at_s=round(t3 + 0.1 * span, 6),
                         action="restore",
                         target=(victim_replica + 1) % 3),
        fleet.ChaosEvent(at_s=t4, action="node_fail",
                         target=fail_target),
        fleet.ChaosEvent(at_s=round(t4 + 0.15 * span, 6),
                         action="node_restore",
                         target=fail_target),
    ]
    alone = run(False)
    mixed = run(True)
    replay = run(True)
    off = run(True, event_core=False)
    tr = mixed["training"]
    p99_alone = _window_p99_ttft(alone["completions"], 0.0,
                                 span + 1.0)
    p99_mixed = _window_p99_ttft(mixed["completions"], 0.0,
                                 span + 1.0)
    serving_held = (p99_alone is not None and p99_mixed is not None
                    and p99_mixed <= 1.25 * p99_alone)
    # strict priority: training was preempted FOR serving at least
    # once (the full-domain node_fail path), and NO serving gang
    # was ever displaced by a training gang
    sched_evs = mixed["scheduler"]["events"]
    train_victims = [e for e in sched_evs
                     if e["type"] == "Preempted"
                     and e["gang"].startswith("train-")]
    strict_preempts = [e for e in train_victims
                       if "preempted by" in e["message"]]
    serving_victims = [e for e in sched_evs
                      if e["type"] == "Preempted"
                      and e["gang"].startswith("replica-")
                      and "preempted by" in e["message"]]
    identical = (_json.dumps(mixed, sort_keys=True)
                 == _json.dumps(replay, sort_keys=True))
    core_identical = (_json.dumps(mixed, sort_keys=True)
                      == _json.dumps(off, sort_keys=True))
    tokens = lambda rep: sum(e["tokens"] for e in rep["completions"])  # noqa: E731
    return {
        "plan": plan.as_dict(),
        "requests": len(trace),
        "drain_node": node_names[drain_target],
        "p99_alone_s": p99_alone,
        "p99_mixed_s": p99_mixed,
        "p99_ratio": (round(p99_mixed / p99_alone, 3)
                      if p99_alone and p99_mixed is not None
                      else None),
        "training": {
            "all_done": tr["all_done"],
            "ledger_ok": tr["ledger_ok"],
            "lost_steps": tr["lost_steps"],
            "rerun_steps": tr["rerun_steps"],
            "evictions": tr["evictions"],
        },
        "train_preemptions": len(train_victims),
        "strict_priority_preemptions": len(strict_preempts),
        "serving_preempted_by_training": len(serving_victims),
        "replay_identical": bool(identical),
        "event_core_identical": bool(core_identical),
        "ok": bool(mixed["ok"] and alone["ok"]
                   and tokens(mixed) == tokens(alone)
                   and tr["all_done"] and tr["ledger_ok"]
                   and tr["lost_steps"] == 0
                   and tr["rerun_steps"] == 0
                   and len(train_victims) >= 2
                   and len(strict_preempts) >= 1
                   and not serving_victims
                   and serving_held
                   and identical and core_identical),
    }


@_scenario("train-globe-spot",
           "an elastic training gang grows onto the globe planner's "
           "idle spot budget; a zone loss checkpoints and evicts it "
           "(zero steps lost), the displaced serving herd pressures "
           "the surviving zone so the planner reclaims the training "
           "rung — the gang shrinks (never aborts) after its zone "
           "returns, finishes with a clean ledger, and the whole "
           "report replays byte-identically")
def _scenario_train_globe_spot(seed: int) -> dict:
    import json as _json

    from kind_tpu_sim import fleet, globe

    plan = ChaosSchedule(seed).plan(kinds=("zone_loss",),
                                    n_faults=1, horizon=6, targets=2)
    tc = fleet.TrainingConfig(gangs=(
        fleet.TrainingGangConfig(name="llm0", total_steps=160,
                                 checkpoint_every=10, elastic=True,
                                 max_topology="4x8"),))
    cfg = globe.GlobeConfig(
        zones=("zone-a", "zone-b"), cells_per_zone=1,
        replicas_per_cell=1, autoscale=True,
        # 3 domains per cell: serving + the base gang still leave a
        # whole domain free, so the spot-granted growth is feasible
        cell_pods=(("tpu-v5-lite-podslice", "4x8"),
                   ("tpu-v5-lite-podslice", "4x8"),
                   ("tpu-v5-lite-podslice", "4x8")),
        planner=globe.PlannerConfig(spot_budget=2,
                                    eval_every_s=0.25),
        training=tc, training_cells=("zone-a/c0",),
        workload=globe.GlobeWorkloadSpec(process="poisson",
                                         rps=25.0, n_per_zone=150))
    traces = globe.generate_globe_traces(cfg, seed)
    span = max(r.arrival_s for reqs in traces.values()
               for r in reqs)
    lost_zone = "zone-a"  # the training zone is the one that dies
    at = round(span * (0.35 + 0.05 * (plan.events[0].at % 3)), 6)
    restore = round(max(2.0 * span / 3.0, at + 0.2 * span), 6)
    events = [
        globe.GlobeChaosEvent(at_s=at, action="zone_loss",
                              target=lost_zone),
        globe.GlobeChaosEvent(at_s=restore, action="zone_restore",
                              target=lost_zone),
    ]
    rep = globe.GlobeSim(cfg, traces=traces, seed=seed,
                         chaos_events=events).run()
    replay = globe.GlobeSim(cfg, traces=traces, seed=seed,
                            chaos_events=events).run()
    g = rep["cells"]["zone-a/c0"]["training"]["gangs"]["llm0"]
    planner = rep["planner"]
    grants = sum(1 for e in planner["events"]
                 if e["action"] == "train_grant")
    reclaims = sum(1 for e in planner["events"]
                   if e["action"] == "train_reclaim")
    identical = (_json.dumps(rep, sort_keys=True)
                 == _json.dumps(replay, sort_keys=True))
    return {
        "plan": plan.as_dict(),
        "requests": rep["requests"],
        "loss_at_s": at,
        "restore_at_s": restore,
        "train_grants": grants,
        "train_reclaims": reclaims,
        "grows": g["grows"],
        "shrinks": g["shrinks"],
        "evictions": g["evictions"],
        "final_topology": g["topology"],
        "lost_steps": g["lost_steps"],
        "ledger_ok": g["ledger_verify"]["ok"],
        "gang_done": g["state"] == "done",
        "replay_identical": bool(identical),
        "ok": bool(rep["ok"] and g["state"] == "done"
                   and g["ledger_verify"]["ok"]
                   and g["lost_steps"] == 0
                   and g["grows"] >= 1
                   and grants >= 1
                   and g["evictions"] >= 1
                   and identical),
    }


@_scenario("sdc-training-bisect",
           "a defective chip seeded into a training gang perturbs "
           "the seeded loss stream; the closed-form loss-spike "
           "checker fires, the gang rolls back at most one "
           "checkpoint cadence of steps (the corrupted step never "
           "commits), deterministic bisection re-runs — priced as "
           "real chip-seconds in the ledger — name the exact seeded "
           "culprit chip in ceil(log2(chips)) rounds, the chip is "
           "quarantined chip-granularly, the ledger verifies clean, "
           "and the report is byte-identical on replay AND with the "
           "event core off")
def _scenario_sdc_training_bisect(seed: int) -> dict:
    import json as _json
    import math as _math

    from kind_tpu_sim import fleet

    plan = ChaosSchedule(seed).plan(
        kinds=("sdc_chip",), n_faults=1, horizon=8, targets=4)
    spec = fleet.WorkloadSpec(process="poisson", rps=40.0,
                              n_requests=120, prompt_len=(8, 24),
                              max_new=(4, 12))
    trace = fleet.generate_trace(spec, seed)
    sim_cfg = fleet.SimReplicaConfig(max_slots=4,
                                     prefill_per_tok_s=0.002,
                                     tpot_s=0.002)
    sc = fleet.FleetSchedConfig(
        pods=(("tpu-v5-lite-podslice", "4x8"),
              ("tpu-v5-lite-podslice", "4x8")))
    cadence = 10
    gang = fleet.TrainingGangConfig(name="llm0", total_steps=90,
                                    checkpoint_every=cadence)
    tc = fleet.TrainingConfig(gangs=(gang,))
    t_sdc = round(0.5 + 0.1 * plan.events[0].at, 6)
    frac = max(0.2, plan.events[0].param)
    events = [fleet.ChaosEvent(at_s=t_sdc, action="sdc_train_chip",
                               target=plan.events[0].target,
                               param=frac)]

    def run(event_core=None):
        fc = fleet.FleetConfig(
            replicas=2, policy="least-outstanding", tick_s=0.01,
            sim=sim_cfg, slo=fleet.SloPolicy(ttft_s=1.0, e2e_s=5.0),
            sched=sc, training=tc, max_virtual_s=120.0,
            event_core=event_core,
            fast_forward=(False if event_core is False else None))
        return fleet.FleetSim(fc, trace,
                              chaos_events=events).run()

    rep = run()
    replay = run()
    off = run(event_core=False)
    g = rep["training"]["gangs"]["llm0"]
    sdc = g.get("sdc", {})
    culprits = sdc.get("culprits", [])
    # the culprit the bisection MUST name is a pure function of
    # (gang, target): the same crc32 draw apply_sdc made
    from kind_tpu_sim import topology as _topo
    chips = _topo.make_slice(gang.accelerator,
                             gang.topology).num_chips
    expected_chip = zlib.crc32(
        f"sdc:train-llm0:{plan.events[0].target}".encode(
            "utf-8")) % chips
    exact = (len(culprits) == 1
             and culprits[0]["chip"] == expected_chip
             and not sdc.get("active_defects"))
    # rollback loses AT MOST one cadence of steps (the corrupted
    # step itself never commits, so strictly < cadence)
    lost_ok = all(c["lost_steps"] < cadence for c in culprits)
    # binary search over a power-of-2 chip count: exactly
    # ceil(log2(chips)) pricing rounds, every one in the ledger
    want_rounds = int(_math.ceil(_math.log2(chips)))
    bisects = [r for r in g["ledger"] if r["kind"] == "bisect"]
    rounds_ok = (sdc.get("bisection_rounds") == want_rounds
                 and len(bisects) == want_rounds
                 and all(b["chip_s"] > 0 for b in bisects))
    integ = rep.get("integrity", {})
    counters = integ.get("counters", {})
    identical = (_json.dumps(rep, sort_keys=True)
                 == _json.dumps(replay, sort_keys=True))
    core_identical = (_json.dumps(rep, sort_keys=True)
                      == _json.dumps(off, sort_keys=True))
    return {
        "plan": plan.as_dict(),
        "sdc_at_s": t_sdc,
        "corrupt_frac": round(frac, 6),
        "expected_chip": expected_chip,
        "culprits": culprits,
        "bisection_rounds": sdc.get("bisection_rounds"),
        "expected_rounds": want_rounds,
        "bisect_chip_s": round(sum(b["chip_s"]
                                   for b in bisects), 6),
        "lost_steps": g["lost_steps"],
        "integrity": counters,
        "ledger_ok": g["ledger_verify"]["ok"],
        "gang_done": g["state"] == "done",
        "replay_identical": bool(identical),
        "event_core_identical": bool(core_identical),
        "ok": bool(rep["ok"] and g["state"] == "done"
                   and g["ledger_verify"]["ok"]
                   and exact and lost_ok and rounds_ok
                   and counters.get("sdc_detections", 0) >= 1
                   and counters.get("chips_quarantined", 0) >= 1
                   and identical and core_identical),
    }


@_scenario("sdc-serving-audit",
           "a serving replica's chip silently corrupts its answers; "
           "the sampled duplicate-compute audit lane catches the "
           "mismatch, withholds the corrupted response, and "
           "quarantines the chip — NOTHING corrupted serves after "
           "detection — while the audit-off contrast run provably "
           "serves every corrupted answer; and the audit tax keeps "
           "p99 TTFT within 1.25x of audit-off, byte-identical on "
           "replay")
def _scenario_sdc_serving_audit(seed: int) -> dict:
    import json as _json

    from kind_tpu_sim import fleet

    plan = ChaosSchedule(seed).plan(
        kinds=("sdc_chip",), n_faults=1, horizon=8, targets=3)
    spec = fleet.WorkloadSpec(process="poisson", rps=30.0,
                              n_requests=200, prompt_len=(8, 24),
                              max_new=(4, 12))
    trace = fleet.generate_trace(spec, seed)
    span = max(r.arrival_s for r in trace)
    sim_cfg = fleet.SimReplicaConfig(max_slots=4,
                                     prefill_per_tok_s=0.002,
                                     tpot_s=0.002)
    victim = plan.events[0].target % 3
    frac = max(0.3, plan.events[0].param)
    t_sdc = round(span * 0.25, 6)
    events = [fleet.ChaosEvent(at_s=t_sdc, action="sdc_chip",
                               target=victim, param=frac)]

    def run(audit_frac):
        fc = fleet.FleetConfig(
            replicas=3, policy="least-outstanding", tick_s=0.01,
            sim=sim_cfg, slo=fleet.SloPolicy(ttft_s=1.0, e2e_s=5.0),
            audit_frac=audit_frac, max_virtual_s=120.0)
        return fleet.FleetSim(fc, trace,
                              chaos_events=events).run()

    audit = run(0.4)
    replay = run(0.4)
    off = run(0.0)
    c_on = audit["integrity"]["counters"]
    c_off = off["integrity"]["counters"]
    detections = audit["integrity"]["detections"]
    # containment: the audit lane caught corrupted work before it
    # served, named the defective chip, and pulled it — after
    # detection NOTHING corrupted serves (an unsampled escape
    # BEFORE detection is the audit_frac trade-off, and must stay
    # strictly below the audit-off tally); audits off, the same
    # seeded defect provably reaches users uncaught
    detect_s = {d["replica"]: d["at_s"] for d in detections}
    post = [e for e in audit["completions"]
            if e.get("corrupted") and not e.get("sdc_caught")
            and e["finish_s"] > detect_s.get(e["replica"],
                                             float("inf"))]
    # detection can come from EITHER side of the duplicate compute:
    # a sampled corrupted original (corrupted_caught) or a clean
    # original whose copy ran on the defective chip — both end in a
    # mismatch and the quarantine, so the gate is mismatch-based
    contained = (c_on.get("audit_mismatches", 0) >= 1
                 and c_on.get("chips_quarantined", 0) >= 1
                 and victim in detect_s
                 and not post
                 and c_on.get("corrupted_served", 0)
                 < c_off.get("corrupted_served", 0))
    escaped = (c_off.get("corrupted_served", 0) >= 1
               and c_off.get("corrupted_caught", 0) == 0)
    p99_on = _window_p99_ttft(audit["completions"], 0.0,
                              span + 1.0)
    p99_off = _window_p99_ttft(off["completions"], 0.0,
                               span + 1.0)
    tax_ok = (p99_on is not None and p99_off is not None
              and p99_on <= 1.25 * p99_off)
    identical = (_json.dumps(audit, sort_keys=True)
                 == _json.dumps(replay, sort_keys=True))
    return {
        "plan": plan.as_dict(),
        "sdc_at_s": t_sdc,
        "victim_replica": victim,
        "corrupt_frac": round(frac, 6),
        "audit": {"frac": 0.4, "counters": c_on,
                  "detections": detections},
        "audit_off": {"counters": c_off},
        "corrupted_served_on": c_on.get("corrupted_served", 0),
        "corrupted_served_off": c_off.get("corrupted_served", 0),
        "p99_audit_s": p99_on,
        "p99_off_s": p99_off,
        "p99_ratio": (round(p99_on / p99_off, 3)
                      if p99_on and p99_off else None),
        "replay_identical": bool(identical),
        "ok": bool(audit["ok"] and off["ok"]
                   and c_on.get("audits", 0) >= 1
                   and contained and escaped and tax_ok
                   and identical),
    }


@_scenario("correlated-rack-loss",
           "one correlated domain fault takes out a whole rack's "
           "nodes at once; the contrast run fails the SAME nodes "
           "for the SAME per-node outage, drawn independently "
           "(staggered) — the correlated draw is strictly worse: "
           "more capacity dead simultaneously and a worse fault-"
           "window p99 / SLO attainment, byte-identical on replay")
def _scenario_correlated_rack_loss(seed: int) -> dict:
    import json as _json

    from kind_tpu_sim import fleet

    plan = ChaosSchedule(seed).plan(
        kinds=("correlated_domain_fault",), n_faults=1, horizon=8,
        targets=2)
    # heavy enough that losing a rack's worth of replicas SHOWS:
    # at light load the crunch hides inside idle slot headroom
    spec = fleet.WorkloadSpec(process="poisson", rps=90.0,
                              n_requests=400, prompt_len=(8, 24),
                              max_new=(4, 12))
    trace = fleet.generate_trace(spec, seed)
    span = max(r.arrival_s for r in trace)
    sim_cfg = fleet.SimReplicaConfig(max_slots=4,
                                     prefill_per_tok_s=0.002,
                                     tpot_s=0.002)
    # four 1-host pods, racked in pairs: every replica is a whole
    # node, so a rack is exactly two replicas' worth of hardware
    sc = fleet.FleetSchedConfig(
        pods=(("tpu-v5-lite-podslice", "2x4"),) * 4, rack_pods=2)

    def run(events):
        fc = fleet.FleetConfig(
            replicas=3, policy="least-outstanding", tick_s=0.01,
            sim=sim_cfg, slo=fleet.SloPolicy(ttft_s=1.0, e2e_s=5.0),
            sched=sc, max_virtual_s=120.0)
        return fleet.FleetSim(fc, trace,
                              chaos_events=events).run()

    # a clean probe run resolves which rack actually HOSTS serving
    # replicas — the blast radius must displace real capacity, not
    # idle nodes — and the independent contrast must then fail the
    # SAME hardware
    probe = fleet.FleetSim(fleet.FleetConfig(replicas=3, sched=sc),
                           [])
    fds = probe.sched.inv.failure_domains()
    node_names = sorted(
        n["name"]
        for d in probe.sched.inv.as_dict()["domains"].values()
        for n in d["nodes"])
    clean = run([])
    replica_nodes = {
        n for e in clean["scheduler"]["events"]
        if e["type"] == "Scheduled"
        and e["gang"].startswith("replica-")
        for n in e["nodes"]}
    fd = max(fds, key=lambda f: (len(
        set(probe.sched.inv.failure_domain_nodes(f))
        & replica_nodes), f))
    target = fds.index(fd)
    rack_nodes = sorted(probe.sched.inv.failure_domain_nodes(fd))
    idxs = [node_names.index(n) for n in rack_nodes]
    dur = round(span * 0.2, 6)
    t0 = round(span * 0.3, 6)
    correlated = [
        fleet.ChaosEvent(at_s=t0, action="domain_fault",
                         target=target),
        fleet.ChaosEvent(at_s=round(t0 + dur, 6),
                         action="domain_restore",
                         target=target),
    ]
    # the independent draw: same nodes, same per-node outage DUR,
    # but staggered — never more than one down at once
    independent = []
    for k, idx in enumerate(idxs):
        at = round(t0 + k * dur, 6)
        independent.append(fleet.ChaosEvent(
            at_s=at, action="node_fail", target=idx))
        independent.append(fleet.ChaosEvent(
            at_s=round(at + dur, 6), action="node_restore",
            target=idx))
    rep_c = run(correlated)
    replay = run(correlated)
    rep_i = run(independent)
    # worst window: requests arriving DURING the correlated outage
    # — when the whole rack is dark vs one node of it
    p99_c = _window_p99_ttft(rep_c["completions"], t0, t0 + dur)
    p99_i = _window_p99_ttft(rep_i["completions"], t0, t0 + dur)

    def _attain(rep):
        comps = rep["completions"]
        return (sum(1 for e in comps if e["slo_ok"])
                / max(1, len(comps)))

    att_c = round(_attain(rep_c), 6)
    att_i = round(_attain(rep_i), 6)
    # strictly worse: the whole rack is dead AT ONCE (len(idxs)
    # simultaneous vs 1 staggered — structural, by construction)
    # and the service FELT it — strictly worse fault-window p99,
    # with whole-run attainment as the saturated-fleet fallback
    worse = ((p99_c is not None and p99_i is not None
              and p99_c > p99_i)
             or att_c < att_i)
    identical = (_json.dumps(rep_c, sort_keys=True)
                 == _json.dumps(replay, sort_keys=True))
    return {
        "plan": plan.as_dict(),
        "failure_domain": fd,
        "rack_nodes": rack_nodes,
        "outage_s": dur,
        "fault_at_s": t0,
        "max_simultaneous_dead": {"correlated": len(idxs),
                                  "independent": 1},
        "p99_window_s": {"correlated": p99_c,
                         "independent": p99_i},
        "slo_attainment": {"correlated": att_c,
                           "independent": att_i},
        "domain_faults": rep_c["integrity"]["counters"].get(
            "domain_faults", 0),
        "replay_identical": bool(identical),
        "ok": bool(rep_c["ok"] and rep_i["ok"]
                   and len(idxs) >= 2 and worse
                   and rep_c["integrity"]["counters"].get(
                       "domain_faults", 0) >= 1
                   and identical),
    }


def run_scenario(name: str, seed: Optional[int] = None) -> dict:
    """Run one named scenario; the report carries the seed, the
    derived fault plan, the recovery-log delta (fault/recovery event
    counts attributable to THIS run), and the invariant verdict."""
    # executor resolution goes through the scenario registry
    # (scenarios/registry.py, lazily imported — the registry itself
    # imports this module): legacy names keep their original
    # functions (byte-identical reports), declarative specs compile
    # through run_spec
    from kind_tpu_sim.scenarios import registry

    fn = registry.executor(name)
    seed = resolve_seed(seed)
    before = metrics.recovery_log().counts()
    report = fn(seed)
    report.update({
        "scenario": name,
        "seed": seed,
        "recovery_events": metrics.recovery_log().snapshot_since(
            before),
    })
    return report


def soak(iterations: int = 10, seed: Optional[int] = None,
         include_slow: bool = False) -> dict:
    """Repeated seeded scenario runs (the `chaos soak` CLI): the
    iteration stream is itself derived from the seed, so a soak that
    finds a failure names the exact (scenario, seed) pair to replay
    with `chaos run`."""
    from kind_tpu_sim.scenarios import registry

    seed = resolve_seed(seed)
    rng = random.Random(zlib.crc32(f"soak:{seed}".encode("utf-8")))
    # the pick pool derives from the registry (sorted), so a new
    # scenario can never be silently missing from soak — and the
    # seeded stream stays a pure function of the registry contents
    names = registry.soak_names(include_slow=include_slow)
    runs = []
    failures = 0
    for i in range(iterations):
        name = rng.choice(names)
        sub_seed = rng.randrange(2 ** 31)
        report = run_scenario(name, seed=sub_seed)
        runs.append({"scenario": name, "seed": sub_seed,
                     "ok": report["ok"]})
        if not report["ok"]:
            failures += 1
            log.error("soak failure: replay with "
                      "`chaos run --scenario %s --seed %d`",
                      name, sub_seed)
    return {
        "seed": seed,
        "iterations": iterations,
        "failures": failures,
        "runs": runs,
        "recovery_events": metrics.recovery_log().counts(),
        "ok": failures == 0,
    }
