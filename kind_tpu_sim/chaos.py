"""Fault injection for the simulated cluster.

The reference has no fault-injection capability (SURVEY.md §5 "failure
detection — minimal"); this subsystem exceeds it deliberately:

* ``fail`` / ``heal`` — drive the device plugin's health channel by
  writing device IDs into the node's unhealthy file
  (manifests.UNHEALTHY_FILE). The plugin's ListAndWatch poller picks
  the change up within ~1s and kubelet reduces the node's allocatable
  count — the durable-capacity behavior a one-shot status patch
  (kind-gpu-sim.sh:113,116) cannot model.
* ``kill-node`` / ``start-node`` — stop/start the kind node container
  itself to exercise scheduler failover of accelerator pods.
"""

from __future__ import annotations

import logging
from typing import List, Optional

from kind_tpu_sim import manifests
from kind_tpu_sim.cluster import ClusterManager
from kind_tpu_sim.config import SimConfig
from kind_tpu_sim.runtime import ContainerRuntime

log = logging.getLogger("kind-tpu-sim")


class ChaosManager:
    def __init__(self, cfg: SimConfig, runtime: ContainerRuntime,
                 cluster: ClusterManager):
        self.cfg = cfg
        self.rt = runtime
        self.cluster = cluster

    def resolve_node(self, node: Optional[str],
                     worker: Optional[int]) -> str:
        if node:
            return node
        workers = self.cluster.worker_nodes()
        if worker is None:
            raise ValueError("specify --node or --worker")
        if not 0 <= worker < len(workers):
            raise ValueError(
                f"--worker {worker} out of range ({len(workers)} workers)"
            )
        return workers[worker]

    def fail_devices(self, node: str, device_ids: List[str]) -> None:
        """Mark devices unhealthy on a node (empty list = all).

        Device IDs come from ``MultiSlice.device_ids`` on the node's
        GLOBAL worker index — the job-level scheme the plugin uses
        (``DevicePlugin::DeviceIds``), valid on every slice of a
        ``--num-slices > 1`` cluster (per-slice ``Slice.device_ids``
        would reject nodes of slice >= 1).
        """
        if not device_ids:
            workers = self.cluster.worker_nodes()
            device_ids = self.cfg.multislice.device_ids(
                workers.index(node))
        content = "\n".join(device_ids) + "\n"
        self.rt.run(
            "exec", node, "mkdir", "-p", manifests.SIM_STATE_DIR
        )
        self.rt.run(
            "exec", "-i", node, "sh", "-c",
            f"cat > {manifests.UNHEALTHY_FILE}",
            input_text=content,
        )
        log.info("marked %d device(s) unhealthy on %s",
                 len(device_ids), node)

    def heal(self, node: str) -> None:
        self.rt.run(
            "exec", node, "sh", "-c",
            f"rm -f {manifests.UNHEALTHY_FILE}",
        )
        log.info("healed all devices on %s", node)

    def kill_node(self, node: str) -> None:
        self.rt.run("stop", node)
        log.info("stopped node container %s", node)

    def start_node(self, node: str) -> None:
        self.rt.run("start", node)
        log.info("started node container %s", node)
