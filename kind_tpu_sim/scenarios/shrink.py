"""Auto-shrinker: violation -> minimal pinned repro (docs/FUZZ.md).

Given a spec that violates some invariants, greedily minimize it
while the violation persists, in a FIXED mutation order (no
randomness), so two shrinks of the same violation produce the
byte-identical repro:

1. **Drop faults** one at a time, to fixpoint — the repro keeps
   only the faults that actually interact.
2. **Narrow windows** — each surviving fault's window is halved
   toward its start, a bounded number of binary steps.
3. **Shrink the trace** — halve ``n_requests`` (floor 20) while
   the violation still reproduces.

The predicate re-runs the spec and re-checks ONLY the originally
violated invariant names (rerun-needing ones get a rerun hook, so a
replay or event-core divergence keeps bisecting via the replaycheck
machinery while it shrinks). The result is emitted as a repro dict
that `chaos fuzz --emit-repros` pins under ``tests/repros/`` — a
spec file the test suite re-runs forever.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Tuple

from kind_tpu_sim.scenarios import invariants
from kind_tpu_sim.scenarios.spec import (FaultWindow, ScenarioSpec,
                                         run_spec)

_MAX_WINDOW_STEPS = 4
_MIN_REQUESTS = 20


def _violated(spec: ScenarioSpec,
              names: Tuple[str, ...]) -> List[str]:
    """Which of ``names`` still fail on a fresh run of ``spec``
    (empty = the candidate lost the violation)."""
    try:
        report = run_spec(spec)
        found = invariants.check(
            spec, report,
            rerun=lambda ec, s=spec: run_spec(s, event_core=ec),
            names=names)
    except Exception:
        # a mutation that cannot even run is not a repro
        return []
    return [v["invariant"] for v in found]


def _with_faults(spec: ScenarioSpec, faults) -> ScenarioSpec:
    return dataclasses.replace(spec, faults=tuple(faults))


def shrink(spec: ScenarioSpec, violated: Tuple[str, ...],
           ) -> Dict[str, object]:
    """Minimize ``spec`` while any of ``violated`` still fails.
    Deterministic: fixed mutation order, no randomness — the
    contract the shrinker-minimality tests pin."""
    current = spec
    steps = 0       # accepted mutations
    attempts = 0    # candidate runs tried

    # 1. drop faults to fixpoint
    changed = True
    while changed:
        changed = False
        for i in range(len(current.faults)):
            cand = _with_faults(
                current, current.faults[:i] + current.faults[i + 1:])
            attempts += 1
            if _violated(cand, violated):
                current = cand
                steps += 1
                changed = True
                break

    # 2. narrow each surviving window toward its start
    for i in range(len(current.faults)):
        for _ in range(_MAX_WINDOW_STEPS):
            f = current.faults[i]
            width = f.end_frac - f.start_frac
            if width <= 0.02:
                break
            cand_fault = FaultWindow(
                kind=f.kind, start_frac=f.start_frac,
                end_frac=round(f.start_frac + width / 2, 4),
                target=f.target, param=f.param)
            cand = _with_faults(
                current, current.faults[:i] + (cand_fault,)
                + current.faults[i + 1:])
            attempts += 1
            if not _violated(cand, violated):
                break
            current = cand
            steps += 1

    # 3. halve the trace
    while current.workload.n_requests > _MIN_REQUESTS:
        half = max(_MIN_REQUESTS,
                   current.workload.n_requests // 2)
        cand = dataclasses.replace(
            current, workload=dataclasses.replace(
                current.workload, n_requests=half))
        attempts += 1
        if not _violated(cand, violated):
            break
        current = cand
        steps += 1

    final = dataclasses.replace(
        current,
        name=f"{spec.name}-min",
        description=(f"auto-shrunk repro of {spec.name} "
                     f"(violated: {', '.join(violated)})"))
    return {
        "spec": final.as_dict(),
        "violated": list(_violated(final, violated)),
        "shrink_steps": steps,
        "attempts": attempts,
        "source": spec.name,
    }
