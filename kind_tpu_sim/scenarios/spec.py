"""ScenarioSpec — the declarative scenario layer (docs/FUZZ.md).

A :class:`ScenarioSpec` names one chaos experiment as pure data:
workload x topology x fault schedule x invariant set. Two flavors
share the class:

* ``kind="spec"`` — fully declarative. :func:`run_spec` compiles the
  spec into a concrete simulation: it generates the seeded workload,
  resolves every :class:`FaultWindow` (windows are FRACTIONS of the
  trace span, so the same spec scales across workloads) into
  fleet/globe chaos events, runs the sim, and returns the report.
  This is what the fuzzer (scenarios/fuzz.py) draws and what shrunk
  repros under ``tests/repros/`` pin.
* ``kind="legacy"`` — one of the ~20 hand-written scenarios in
  ``chaos.py``. The spec carries the scenario's declarative metadata
  (fault kinds, scope, named invariants, replayability) while the
  original function stays the executor, so every legacy name keeps
  producing byte-identical reports through the registry
  (scenarios/registry.py).

Everything here is a pure function of (spec, seed): specs round-trip
through sorted-keys JSON (:meth:`ScenarioSpec.as_dict` /
:meth:`ScenarioSpec.from_dict`), which is the repro-pinning contract
— a violation the fuzzer shrinks is replayable from its file alone.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from kind_tpu_sim.chaos import FAULT_KINDS, FAULT_SCHEMAS

SPEC_KINDS = ("spec", "legacy")

# Serving-replica service shape shared by every compiled spec: the
# fuzzer varies load and faults, not the replica micro-model.
_PROMPT_LEN = (4, 16)
_MAX_NEW = (4, 10)

# The generation mix every zoo-flavored spec runs on: replicas
# (fleet) or cells (globe) cycle small-HBM/big-HBM so the
# HBM-fit ladder in default_zoo() is actually exercised.
_SPEC_GENERATIONS = ("v5e", "v5p")


@dataclasses.dataclass(frozen=True)
class WorkloadDims:
    """The workload axes a spec varies: arrival process, rate, trace
    length, and the per-request deadline. Lengths stay at the module
    defaults — the fuzzer explores load shape, not token shape."""

    process: str = "poisson"     # poisson | bursty | diurnal
    rps: float = 40.0
    n_requests: int = 100
    deadline_s: Optional[float] = None

    def as_dict(self) -> dict:
        return {
            "process": self.process,
            "rps": self.rps,
            "n_requests": self.n_requests,
            "deadline_s": self.deadline_s,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "WorkloadDims":
        return cls(process=d["process"], rps=float(d["rps"]),
                   n_requests=int(d["n_requests"]),
                   deadline_s=d.get("deadline_s"))


@dataclasses.dataclass(frozen=True)
class TopologySpec:
    """Where the spec runs: one serving fleet (optionally
    scheduler-backed, the prerequisite for node/link/training
    faults) or a multi-zone globe."""

    kind: str = "fleet"          # fleet | globe
    replicas: int = 2            # fleet replicas / globe per cell
    sched: bool = False          # fleet only (globe cells always are)
    zones: int = 2               # globe only
    cells_per_zone: int = 1      # globe only
    disagg: bool = False         # fleet only; phase-split pools
    tenancy: bool = False        # fleet only; default_tenancy() pop
    # model zoo (docs/ZOO.md): default_zoo() traffic on a mixed
    # v5e/v5p fleet (fleet) or cells cycled over both generations
    # (globe) — the prerequisite for the zoo fault kinds
    zoo: bool = False
    # sampled duplicate-compute integrity audits (docs/SDC.md):
    # this fraction of served requests re-executes on a second
    # replica and CRC-compares — the serving-side SDC detection
    # channel. 0 (the default) keeps the audit lane off and every
    # pre-SDC pinned spec byte-identical.
    audit_frac: float = 0.0

    def as_dict(self) -> dict:
        out = {
            "kind": self.kind,
            "replicas": self.replicas,
            "sched": self.sched,
            "zones": self.zones,
            "cells_per_zone": self.cells_per_zone,
            "disagg": self.disagg,
            "tenancy": self.tenancy,
        }
        # conditional so every pre-zoo pinned spec keeps its bytes
        if self.zoo:
            out["zoo"] = True
        if self.audit_frac:
            out["audit_frac"] = self.audit_frac
        return out

    @classmethod
    def from_dict(cls, d: dict) -> "TopologySpec":
        return cls(kind=d["kind"], replicas=int(d["replicas"]),
                   sched=bool(d["sched"]), zones=int(d["zones"]),
                   cells_per_zone=int(d["cells_per_zone"]),
                   disagg=bool(d.get("disagg", False)),
                   tenancy=bool(d.get("tenancy", False)),
                   zoo=bool(d.get("zoo", False)),
                   audit_frac=float(d.get("audit_frac", 0.0)))


@dataclasses.dataclass(frozen=True)
class FaultWindow:
    """One timed fault: ``kind`` (a FAULT_KINDS member) strikes
    ``target`` over ``[start_frac, end_frac]`` of the trace span.
    Fractions keep the window meaningful under trace shrinking —
    the shrinker halves ``n_requests`` without re-deriving the
    schedule. ``param`` is the kind's magnitude per its
    FaultSchema (0 = no magnitude)."""

    kind: str
    start_frac: float
    end_frac: float
    target: int = 0
    param: float = 0.0

    def __post_init__(self) -> None:
        if self.kind not in FAULT_KINDS:
            raise ValueError(
                f"unknown fault kind {self.kind!r}; known: "
                f"{', '.join(FAULT_KINDS)}")
        if not 0.0 <= self.start_frac <= self.end_frac <= 1.0:
            raise ValueError(
                f"fault window [{self.start_frac}, {self.end_frac}]"
                " must satisfy 0 <= start <= end <= 1")

    def as_dict(self) -> dict:
        return {
            "kind": self.kind,
            "start_frac": self.start_frac,
            "end_frac": self.end_frac,
            "target": self.target,
            "param": self.param,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "FaultWindow":
        return cls(kind=d["kind"],
                   start_frac=float(d["start_frac"]),
                   end_frac=float(d["end_frac"]),
                   target=int(d["target"]),
                   param=float(d["param"]))


@dataclasses.dataclass(frozen=True)
class ScenarioSpec:
    """One named chaos experiment as data. ``invariants`` names
    entries of the invariant catalog (scenarios/invariants.py)
    checked on every run; for ``kind="spec"`` the UNIVERSAL set is
    checked regardless (that is what universal means)."""

    name: str
    description: str = ""
    kind: str = "spec"
    seed: int = 0
    topology: TopologySpec = TopologySpec()
    workload: WorkloadDims = WorkloadDims()
    faults: Tuple[FaultWindow, ...] = ()
    fault_kinds: Tuple[str, ...] = ()   # legacy metadata only
    training_gangs: int = 0
    overload: bool = False
    invariants: Tuple[str, ...] = ()
    needs_jax: bool = False
    slow: bool = False
    replayable: bool = False
    max_virtual_s: float = 240.0

    def __post_init__(self) -> None:
        if self.kind not in SPEC_KINDS:
            raise ValueError(
                f"unknown spec kind {self.kind!r}; known: "
                f"{', '.join(SPEC_KINDS)}")
        for k in self.fault_kinds:
            if k not in FAULT_KINDS:
                raise ValueError(
                    f"unknown fault kind {k!r} in spec "
                    f"{self.name!r}")

    def all_fault_kinds(self) -> Tuple[str, ...]:
        """The kinds this spec exercises: declared metadata for
        legacy scenarios, derived from the windows for spec runs."""
        if self.kind == "legacy":
            return tuple(sorted(set(self.fault_kinds)))
        return tuple(sorted({f.kind for f in self.faults}))

    def as_dict(self) -> dict:
        return {
            "name": self.name,
            "description": self.description,
            "kind": self.kind,
            "seed": self.seed,
            "topology": self.topology.as_dict(),
            "workload": self.workload.as_dict(),
            "faults": [f.as_dict() for f in self.faults],
            "fault_kinds": list(self.fault_kinds),
            "training_gangs": self.training_gangs,
            "overload": self.overload,
            "invariants": list(self.invariants),
            "needs_jax": self.needs_jax,
            "slow": self.slow,
            "replayable": self.replayable,
            "max_virtual_s": self.max_virtual_s,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "ScenarioSpec":
        return cls(
            name=d["name"],
            description=d.get("description", ""),
            kind=d.get("kind", "spec"),
            seed=int(d.get("seed", 0)),
            topology=TopologySpec.from_dict(d["topology"]),
            workload=WorkloadDims.from_dict(d["workload"]),
            faults=tuple(FaultWindow.from_dict(f)
                         for f in d.get("faults", ())),
            fault_kinds=tuple(d.get("fault_kinds", ())),
            training_gangs=int(d.get("training_gangs", 0)),
            overload=bool(d.get("overload", False)),
            invariants=tuple(d.get("invariants", ())),
            needs_jax=bool(d.get("needs_jax", False)),
            slow=bool(d.get("slow", False)),
            replayable=bool(d.get("replayable", False)),
            max_virtual_s=float(d.get("max_virtual_s", 240.0)),
        )


# -- spec validation ---------------------------------------------------


def spec_problems(spec: ScenarioSpec) -> List[str]:
    """Why ``spec`` cannot compile (empty = valid). The fuzzer only
    emits valid specs by construction; this is the gate for repro
    files and hand-written specs."""
    problems: List[str] = []
    if spec.kind == "legacy":
        return problems
    topo = spec.topology
    if topo.kind not in ("fleet", "globe"):
        problems.append(
            f"unknown topology kind {topo.kind!r} (fleet|globe)")
        return problems
    exclusive = 0
    for f in spec.faults:
        schema = FAULT_SCHEMAS.get(f.kind)
        if schema is None:
            problems.append(f"fault kind {f.kind!r} has no schema")
            continue
        if not schema.fuzzable:
            problems.append(
                f"fault kind {f.kind!r} is not composable into a "
                "spec run (FaultSchema.fuzzable is False)")
        if topo.kind not in schema.scopes:
            problems.append(
                f"fault kind {f.kind!r} does not apply to "
                f"{topo.kind!r} topologies (scopes: "
                f"{', '.join(schema.scopes)})")
        if "sched" in schema.needs and (topo.kind == "fleet"
                                        and not topo.sched):
            problems.append(
                f"fault kind {f.kind!r} needs a scheduler-backed "
                "fleet (topology.sched)")
        if "training" in schema.needs and spec.training_gangs <= 0:
            problems.append(
                f"fault kind {f.kind!r} needs training_gangs > 0")
        if "overload" in schema.needs and not spec.overload:
            problems.append(
                f"fault kind {f.kind!r} needs overload controls on")
        if "disagg" in schema.needs and not (topo.kind == "fleet"
                                             and topo.disagg):
            problems.append(
                f"fault kind {f.kind!r} needs a disaggregated "
                "fleet (topology.disagg)")
        if "tenancy" in schema.needs and not (topo.kind == "fleet"
                                              and topo.tenancy):
            problems.append(
                f"fault kind {f.kind!r} needs a tenanted fleet "
                "(topology.tenancy)")
        if "zoo" in schema.needs and not topo.zoo:
            problems.append(
                f"fault kind {f.kind!r} needs a model-zoo "
                "topology (topology.zoo)")
        if "sdc" in schema.needs and topo.kind != "fleet":
            problems.append(
                f"fault kind {f.kind!r} only applies to fleet "
                "topologies (the SDC machinery is per fleet)")
        if schema.exclusive:
            exclusive += 1
    if exclusive > 1:
        problems.append(
            "at most one exclusive fault kind (zone_loss / "
            "herd_failover / demand_surge / noisy_neighbor / "
            "tenant_surge) per spec")
    if topo.tenancy and topo.kind != "fleet":
        problems.append(
            "topology.tenancy only applies to fleet topologies "
            "(globe tenancy runs through GlobeConfig.tenancy)")
    if topo.disagg and topo.kind != "fleet":
        problems.append(
            "topology.disagg only applies to fleet topologies")
    if topo.disagg and topo.sched:
        problems.append(
            "topology.disagg is incompatible with a scheduler-"
            "backed fleet (phased pools pin their own placements)")
    if topo.zoo and topo.disagg:
        problems.append(
            "topology.zoo is incompatible with a disaggregated "
            "fleet (the zoo's warm-pool state is per unified "
            "replica)")
    if topo.audit_frac and topo.kind != "fleet":
        problems.append(
            "topology.audit_frac only applies to fleet topologies")
    if topo.audit_frac and topo.disagg:
        problems.append(
            "topology.audit_frac is incompatible with a "
            "disaggregated fleet (audit copies are whole-request "
            "re-executions on unified replicas)")
    if not 0.0 <= topo.audit_frac <= 1.0:
        problems.append(
            f"topology.audit_frac {topo.audit_frac} must lie in "
            "[0, 1]")
    if topo.zoo and topo.kind == "fleet" and topo.sched:
        problems.append(
            "topology.zoo spec fleets pin generations directly; "
            "scheduler-backed zoo fleets run through FleetConfig "
            "(FleetSchedConfig.replica_accelerator)")
    if spec.training_gangs and topo.kind == "fleet" and not topo.sched:
        problems.append(
            "training_gangs need a scheduler-backed fleet")
    if spec.training_gangs and topo.kind == "globe":
        problems.append(
            "spec runs keep training on fleet topologies "
            "(globe training needs bespoke cell headroom)")
    if topo.kind == "globe" and topo.zones < 2:
        # zone-scale faults need a spill destination; the compiler
        # (_globe_events) always spares zone 0, which only works
        # when another zone exists
        if any(f.kind in ("zone_loss", "herd_failover", "cell_drain",
                          "generation_cell_drain")
               for f in spec.faults):
            problems.append(
                "zone-scale faults need at least 2 zones (zone 0 "
                "is always spared as the spill destination)")
    return problems


# -- compiling a spec into a run --------------------------------------


def _trace_span(trace) -> float:
    if not trace:
        return 0.0
    return max(r.arrival_s for r in trace)


def _fleet_events(spec: ScenarioSpec, span: float):
    """FaultWindow -> fleet ChaosEvents. Strike at start, heal at
    end; replica_flap cycles twice inside its window; the train
    kinds are instantaneous (the gang guard/rollback machinery IS
    the recovery)."""
    from kind_tpu_sim import fleet

    events = []
    replicas = max(1, spec.topology.replicas)
    for f in sorted(spec.faults,
                    key=lambda w: (w.start_frac, w.kind, w.target)):
        t0 = round(span * f.start_frac, 6)
        t1 = round(span * f.end_frac, 6)
        if f.kind == "replica_preempt":
            rid = f.target % replicas
            events.append(fleet.ChaosEvent(t0, "preempt", rid))
            events.append(fleet.ChaosEvent(t1, "restore", rid))
        elif f.kind == "replica_flap":
            rid = f.target % replicas
            mid0 = round(t0 + (t1 - t0) * 0.4, 6)
            mid1 = round(t0 + (t1 - t0) * 0.6, 6)
            events.append(fleet.ChaosEvent(t0, "preempt", rid))
            events.append(fleet.ChaosEvent(mid0, "restore", rid))
            events.append(fleet.ChaosEvent(mid1, "preempt", rid))
            events.append(fleet.ChaosEvent(t1, "restore", rid))
        elif f.kind == "slow_replica":
            rid = f.target % replicas
            events.append(fleet.ChaosEvent(
                t0, "slow", rid, max(1.0, f.param)))
            events.append(fleet.ChaosEvent(t1, "unslow", rid))
        elif f.kind == "node_drain":
            node = f.target % 4   # default 4x8 pod = 4 hosts
            events.append(fleet.ChaosEvent(t0, "node_drain", node))
            events.append(fleet.ChaosEvent(t1, "node_restore",
                                           node))
        elif f.kind == "node_fail":
            node = f.target % 4
            events.append(fleet.ChaosEvent(t0, "node_fail", node))
            events.append(fleet.ChaosEvent(t1, "node_restore",
                                           node))
        elif f.kind == "degraded_link":
            events.append(fleet.ChaosEvent(
                t0, "link_degrade", 0, max(0.01, f.param)))
            events.append(fleet.ChaosEvent(t1, "link_restore", 0))
        elif f.kind == "prefill_pool_loss":
            events.append(fleet.ChaosEvent(
                t0, "prefill_pool_loss", 0))
            events.append(fleet.ChaosEvent(
                t1, "prefill_pool_restore", 0))
        elif f.kind == "kv_transfer_degrade":
            events.append(fleet.ChaosEvent(
                t0, "kv_degrade", 0, max(0.01, f.param)))
            events.append(fleet.ChaosEvent(t1, "kv_restore", 0))
        elif f.kind == "train_preempt":
            gang = f.target % max(1, spec.training_gangs)
            events.append(fleet.ChaosEvent(t0, "train_preempt",
                                           gang))
        elif f.kind == "train_kill":
            gang = f.target % max(1, spec.training_gangs)
            events.append(fleet.ChaosEvent(t0, "train_kill", gang))
        elif f.kind == "sdc_chip":
            # instantaneous strike, NO heal: the defect persists
            # until detection quarantines the chip (docs/SDC.md) —
            # on a training fleet it seeds a gang chip, else a
            # serving replica's chip
            if spec.training_gangs > 0:
                # raw target: the trainer hashes it into a gang
                # chip index, any value is a valid seed
                events.append(fleet.ChaosEvent(
                    t0, "sdc_train_chip", f.target,
                    max(0.0, f.param)))
            else:
                # serving strikes must name a live replica — the
                # sim matches replica_id exactly, so an unwrapped
                # fuzz/tune target of 0..7 on a small fleet would
                # silently miss
                events.append(fleet.ChaosEvent(
                    t0, "sdc_chip", f.target % replicas,
                    max(0.0, f.param)))
        elif f.kind == "correlated_domain_fault":
            events.append(fleet.ChaosEvent(
                t0, "domain_fault", f.target))
            events.append(fleet.ChaosEvent(
                t1, "domain_restore", f.target))
        elif f.kind == "model_swap_storm":
            # `param` eviction pulses spread evenly across the
            # window — each one drops every resident model, so the
            # warm pool rebuilds from scratch that many times
            pulses = max(1, int(f.param))
            for k in range(pulses):
                frac = k / max(1, pulses - 1) if pulses > 1 else 0.0
                events.append(fleet.ChaosEvent(
                    round(t0 + (t1 - t0) * frac, 6),
                    "model_swap_evict", 0))
        # demand_surge is a trace transform, not an event
    return events


def _globe_events(spec: ScenarioSpec, span: float, zones, cells):
    from kind_tpu_sim import globe

    events = []
    for f in sorted(spec.faults,
                    key=lambda w: (w.start_frac, w.kind, w.target)):
        t0 = round(span * f.start_frac, 6)
        t1 = round(span * f.end_frac, 6)
        if f.kind in ("zone_loss", "herd_failover"):
            # spare zone 0: the spill destination (spec_problems)
            zone = zones[1 + f.target % max(1, len(zones) - 1)]
            events.append(globe.GlobeChaosEvent(t0, f.kind, zone))
            events.append(globe.GlobeChaosEvent(
                t1, "zone_restore", zone))
        elif f.kind == "dcn_degrade":
            zone = zones[f.target % len(zones)]
            events.append(globe.GlobeChaosEvent(
                t0, "dcn_degrade", zone, max(0.01, f.param)))
            events.append(globe.GlobeChaosEvent(
                t1, "dcn_restore", zone))
        elif f.kind == "cell_drain":
            cell = cells[1 + f.target % max(1, len(cells) - 1)]
            events.append(globe.GlobeChaosEvent(
                t0, "cell_drain", cell))
            events.append(globe.GlobeChaosEvent(
                t1, "cell_undrain", cell))
        elif f.kind == "generation_cell_drain":
            # generation-skewed capacity loss (docs/ZOO.md): every
            # cell of the targeted generation drains at once — the
            # models only that generation fits must ride out the
            # window on warm survivors or shed loudly. Cell 0 is
            # always spared (the spill-destination rule).
            gens = _SPEC_GENERATIONS
            gen = gens[f.target % len(gens)]
            for i, cell in enumerate(cells):
                if i == 0 or gens[i % len(gens)] != gen:
                    continue
                events.append(globe.GlobeChaosEvent(
                    t0, "cell_drain", cell))
                events.append(globe.GlobeChaosEvent(
                    t1, "cell_undrain", cell))
    return events


def _training_config(spec: ScenarioSpec):
    from kind_tpu_sim import fleet

    if not spec.training_gangs:
        return None
    # topology 2x8 = one host ROW on the default 4x8 inventory: it
    # tiles next to the serving replicas' 2x4 placements (the
    # `fleet run --train` shape, cli.py)
    return fleet.TrainingConfig(gangs=tuple(
        fleet.TrainingGangConfig(name=f"gang{i}", topology="2x8",
                                 total_steps=40)
        for i in range(spec.training_gangs)))


def run_spec(spec: ScenarioSpec, seed: Optional[int] = None,
             event_core: Optional[bool] = None) -> Dict[str, object]:
    """Compile and run one declarative spec; the report is a pure
    function of (spec, seed). ``event_core`` forces the event-heap
    core on/off (None = knob default) — the lever the
    event-core-equality invariant pulls."""
    if spec.kind == "legacy":
        raise ValueError(
            f"spec {spec.name!r} is a legacy scenario; run it via "
            "scenarios.registry (chaos.run_scenario)")
    problems = spec_problems(spec)
    if problems:
        raise ValueError(
            f"invalid spec {spec.name!r}: " + "; ".join(problems))
    use_seed = spec.seed if seed is None else int(seed)
    if spec.topology.kind == "globe":
        return _run_globe_spec(spec, use_seed, event_core)
    return _run_fleet_spec(spec, use_seed, event_core)


def _run_fleet_spec(spec: ScenarioSpec, seed: int,
                    event_core: Optional[bool]) -> Dict[str, object]:
    from kind_tpu_sim import fleet

    tenancy = None
    if spec.topology.tenancy:
        from kind_tpu_sim.fleet.tenancy import default_tenancy
        tenancy = default_tenancy()
    zoo = None
    if spec.topology.zoo:
        from kind_tpu_sim.fleet.zoo import default_zoo
        zoo = default_zoo()
    wl = fleet.WorkloadSpec(
        process=spec.workload.process, rps=spec.workload.rps,
        n_requests=spec.workload.n_requests,
        prompt_len=_PROMPT_LEN, max_new=_MAX_NEW,
        deadline_s=spec.workload.deadline_s,
        tenancy=tenancy, zoo=zoo)
    base = fleet.generate_trace(wl, seed)
    span = _trace_span(base)
    surges = [f for f in spec.faults if f.kind == "demand_surge"]
    tsurges = [f for f in spec.faults
               if f.kind in ("noisy_neighbor", "tenant_surge")]
    if surges:
        s = surges[0]
        trace = fleet.surge_trace(
            wl, seed, round(span * s.start_frac, 6),
            round(span * s.end_frac, 6), max(1.0, s.param))
    elif tsurges:
        # the tenant-scoped surge transforms (docs/TENANCY.md):
        # noisy_neighbor floods from the batch scavenger, a
        # tenant_surge strikes the tenant the target indexes
        from kind_tpu_sim.fleet.tenancy import tenant_surge_trace
        s = tsurges[0]
        names = sorted(t.name for t in tenancy.tenants)
        batch = [t.name for t in tenancy.tenants
                 if t.qos == "batch"]
        who = (batch[0] if s.kind == "noisy_neighbor" and batch
               else names[s.target % len(names)])
        trace = tenant_surge_trace(
            wl, seed, round(span * s.start_frac, 6),
            round(span * s.end_frac, 6), max(1.0, s.param), who)
    else:
        trace = base
    if any(f.kind == "correlated_domain_fault"
           for f in spec.faults):
        # domain faults need labeled failure domains: a 4-pod
        # inventory grouped 2 pods per rack (docs/SDC.md), so one
        # draw takes out half the fleet's placements at once
        sched = fleet.FleetSchedConfig(
            pods=(("tpu-v5-lite-podslice", "4x8"),) * 4,
            rack_pods=2)
    elif spec.topology.sched:
        sched = fleet.FleetSchedConfig()
    else:
        sched = None
    disagg = None
    if spec.topology.disagg:
        # even split, prefill-heavy remainder; spec_problems already
        # rejected disagg x sched
        p = max(1, spec.topology.replicas // 2)
        d = max(1, spec.topology.replicas - p)
        disagg = fleet.DisaggConfig(prefill_replicas=p,
                                    decode_replicas=d)
    cfg = fleet.FleetConfig(
        replicas=(disagg.prefill_replicas + disagg.decode_replicas
                  if disagg else spec.topology.replicas),
        policy="least-outstanding",
        sched=sched,
        overload=(fleet.OverloadConfig() if spec.overload
                  else None),
        training=_training_config(spec),
        disagg=disagg,
        tenancy=tenancy,
        zoo=zoo,
        generations=(_SPEC_GENERATIONS if zoo is not None
                     else None),
        audit_frac=(spec.topology.audit_frac
                    if spec.topology.audit_frac else None),
        max_virtual_s=spec.max_virtual_s,
        event_core=event_core)
    events = _fleet_events(spec, span)
    return fleet.FleetSim(cfg, trace, chaos_events=events).run()


def _run_globe_spec(spec: ScenarioSpec, seed: int,
                    event_core: Optional[bool]) -> Dict[str, object]:
    from kind_tpu_sim import globe

    zones = tuple(f"zone-{chr(ord('a') + i)}"
                  for i in range(spec.topology.zones))
    zoo = None
    if spec.topology.zoo:
        from kind_tpu_sim.fleet.zoo import default_zoo
        zoo = default_zoo()
    cfg = globe.GlobeConfig(
        zones=zones,
        cells_per_zone=spec.topology.cells_per_zone,
        replicas_per_cell=spec.topology.replicas,
        overload=(globe.OverloadConfig() if spec.overload
                  else None),
        zoo=zoo,
        generations=(_SPEC_GENERATIONS if zoo is not None
                     else None),
        workload=globe.GlobeWorkloadSpec(
            process=spec.workload.process,
            rps=spec.workload.rps,
            n_per_zone=spec.workload.n_requests,
            prompt_len=_PROMPT_LEN, max_new=_MAX_NEW,
            deadline_s=spec.workload.deadline_s),
        max_virtual_s=spec.max_virtual_s,
        event_core=event_core)
    traces = globe.generate_globe_traces(cfg, seed)
    span = max((_trace_span(t) for t in traces.values()),
               default=0.0)
    cells = cfg.cell_names()
    events = _globe_events(spec, span, list(zones), cells)
    return globe.GlobeSim(cfg, traces=traces, seed=seed,
                          chaos_events=events).run()
