"""Scenario compiler + seeded chaos fuzzer (docs/FUZZ.md).

The declarative scenario layer: :class:`ScenarioSpec` names one
chaos experiment as data (workload x topology x fault schedule x
invariant set), the registry re-expresses every hand-written
``chaos.py`` scenario in it (same names, byte-identical reports),
the invariant catalog gives every scenario assertion a name, and
the fuzzer composes multi-layer fault schedules, checks the
universal invariants on every run, and auto-shrinks violations to
minimal pinned repros under ``tests/repros/``.
"""

from kind_tpu_sim.scenarios.spec import (FaultWindow, ScenarioSpec,
                                         TopologySpec, WorkloadDims,
                                         run_spec, spec_problems)
from kind_tpu_sim.scenarios.invariants import (CATALOG, Invariant,
                                               UNIVERSAL, check)

__all__ = [
    "CATALOG",
    "FaultWindow",
    "Invariant",
    "ScenarioSpec",
    "TopologySpec",
    "UNIVERSAL",
    "WorkloadDims",
    "check",
    "run_spec",
    "spec_problems",
]
