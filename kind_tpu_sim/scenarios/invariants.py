"""The named invariant catalog (docs/FUZZ.md).

Every scenario assertion that used to live as a bespoke ``assert``
inside a chaos.py scenario function has a NAME here, and every spec
(legacy or declarative) declares which names it must satisfy. The
UNIVERSAL set is checked on every fuzzer run regardless of spec —
they are properties of the simulator itself, not of one experiment:

* ``verdict-ok`` — the report's own verdict is green.
* ``no-lost-work`` — zero lost or duplicated work: every traced
  request reaches exactly one terminal outcome (the training
  ledger's conservation law generalized to serving completions),
  and no attempt id is double-logged.
* ``ledger-clean`` — every training ledger verifies, and no gang
  loses steps unless the spec composed a ``train_kill`` (hard
  kills are the ONLY sanctioned step-loss path).
* ``containment`` — overload controls stay inside their budgets:
  token-bucket arithmetic holds (spent <= burst + ratio * earned)
  and the scheduled/suppressed counters reconcile with the buckets.
* ``tenant-accounting`` — tenancy conservation: per-tenant admission
  books (admitted + quota/token sheds) reconcile with the distinct
  base ids reaching terminal outcomes.
* ``no-corruption-escapes`` — once a replica's defective chip is
  detected, no later completion it produced serves a corrupted
  payload uncaught (docs/SDC.md containment).
* ``recovery`` — after the faults lift, the control planes let go:
  no breaker still open, brownout back at level 0.
* ``replay-identical`` — a second run of the same (spec, seed) is
  byte-identical; a violation names the first divergent event via
  the replaycheck bisector (PR 7).
* ``event-core-equality`` — the event-heap core on/off produces the
  identical report (execution strategy, never semantics).

Checks walk the report structurally (any nested sim report — legacy
scenarios embed clean/faulted runs — is checked wherever it
appears), return ``None`` on pass and a human-readable violation
detail on failure. All details are pure functions of the report, so
fuzz reports stay byte-identical across runs of one seed.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Callable, Dict, List, Optional, Tuple

from kind_tpu_sim.analysis import replaycheck
from kind_tpu_sim.scenarios.spec import ScenarioSpec

# Report sub-trees that deliberately break the universal rules:
# controls-off comparison runs (the storm the budgets exist to
# prevent) are evidence, not violations.
_EXEMPT_KEYS = ("uncontrolled", "no_controls", "controls_off")


class InvariantContext:
    """What a check sees: the spec, the report, and (fuzz runs
    only) a ``rerun(event_core)`` hook for the invariants that need
    a second simulation. Reruns are memoized — replay-identical and
    event-core-equality cost one extra run each, once."""

    def __init__(self, spec: ScenarioSpec, report: dict,
                 rerun: Optional[Callable[[Optional[bool]], dict]]
                 = None):
        self.spec = spec
        self.report = report
        self._rerun = rerun
        self._cache: Dict[object, dict] = {}

    @property
    def can_rerun(self) -> bool:
        return self._rerun is not None

    def rerun(self, event_core: Optional[bool] = None) -> dict:
        if self._rerun is None:
            raise ValueError("this context cannot rerun its spec")
        if event_core not in self._cache:
            self._cache[event_core] = self._rerun(event_core)
        return self._cache[event_core]


@dataclasses.dataclass(frozen=True)
class Invariant:
    """One named machine-checkable property. ``check(ctx)`` returns
    None (holds) or the violation detail. ``universal`` invariants
    are checked on every fuzzer run regardless of what the spec
    declares; ``needs_rerun`` ones silently pass when the context
    cannot rerun (legacy reports evaluated post-hoc)."""

    name: str
    description: str
    check: Callable[[InvariantContext], Optional[str]]
    universal: bool = True
    needs_rerun: bool = False


def _walk(obj, path=""):
    """Depth-first (sorted-key) walk yielding (path, dict) for every
    dict in the report, skipping controls-off exemplar sub-trees."""
    if isinstance(obj, dict):
        yield path, obj
        for key in sorted(obj):
            if key in _EXEMPT_KEYS:
                continue
            yield from _walk(obj[key], f"{path}{key}.")
    elif isinstance(obj, list):
        for i, item in enumerate(obj):
            yield from _walk(item, f"{path}{i}.")


def _sim_reports(report: dict):
    """Every (path, dict) that looks like a fleet/globe sim report:
    has both a completion log and a request count."""
    for path, d in _walk(report):
        if (isinstance(d.get("completions"), list)
                and isinstance(d.get("requests"), int)):
            yield path, d


def _check_verdict(ctx: InvariantContext) -> Optional[str]:
    ok = ctx.report.get("ok")
    if ok is True:
        return None
    return f"report verdict ok={ok!r}"


def _check_no_lost_work(ctx: InvariantContext) -> Optional[str]:
    for path, d in _sim_reports(ctx.report):
        log = d["completions"]
        ids = [e.get("request_id") for e in log
               if isinstance(e, dict)]
        if len(set(ids)) != len(ids):
            dupes = sorted({i for i in ids if ids.count(i) > 1})
            return (f"{path or 'report'}: duplicated attempt "
                    f"ids {dupes[:4]}")
        base = {str(i).split("~r", 1)[0] for i in ids}
        if len(base) != d["requests"]:
            return (f"{path or 'report'}: {d['requests']} requests "
                    f"but {len(base)} reached a terminal outcome "
                    "(lost or phantom work)")
    return None


def _check_ledger(ctx: InvariantContext) -> Optional[str]:
    kinds = ctx.spec.all_fault_kinds()
    # hard kills roll back to the last cadence checkpoint; an SDC
    # detection does the same (the corrupted segment is the loss the
    # bisection re-runs, docs/SDC.md) — both are sanctioned
    allow_loss = ("train_kill" in kinds or "sdc_chip" in kinds)
    for path, d in _walk(ctx.report):
        if "ledger_ok" in d:
            if d["ledger_ok"] is not True:
                return (f"{path or 'report'}: training ledger "
                        "failed verification")
            lost = d.get("lost_steps", 0)
            if lost and not allow_loss:
                return (f"{path or 'report'}: {lost} training "
                        "step(s) lost without a train_kill in the "
                        "spec")
    return None


def _bucket_over(bucket: dict, burst: float) -> bool:
    ratio = bucket.get("ratio", 0.0)
    if ratio <= 0.0:
        return False    # disabled bucket: controls-off mode
    cap = burst + ratio * bucket.get("earned", 0) + 1e-9
    return bucket.get("spent", 0) > cap


def _check_containment(ctx: InvariantContext) -> Optional[str]:
    for path, d in _walk(ctx.report):
        if "retry_budget" not in d or "counters" not in d:
            continue
        cfg = d.get("config", {})
        # OverloadConfig.as_dict serializes every field (contractlint
        # `drift` holds it to that), so the bursts come straight from
        # the report — a report without them is itself the bug
        retry_burst = cfg["retry_budget_burst"]
        hedge_burst = cfg["hedge_budget_burst"]
        spent = suppressed = 0
        disabled = False
        for origin in sorted(d["retry_budget"]):
            bucket = d["retry_budget"][origin]
            disabled = disabled or bucket.get("ratio", 0.0) <= 0.0
            spent += bucket.get("spent", 0)
            suppressed += bucket.get("suppressed", 0)
            if _bucket_over(bucket, retry_burst):
                return (f"{path or 'report'}: origin {origin!r} "
                        f"retry bucket overspent ({bucket['spent']}"
                        f" > burst {retry_burst} + ratio x "
                        f"{bucket['earned']} earned)")
        hedge = d.get("hedge_budget", {})
        if _bucket_over(hedge, hedge_burst):
            return (f"{path or 'report'}: hedge budget overspent "
                    f"({hedge['spent']} > burst {hedge_burst} + "
                    f"ratio x {hedge.get('earned', 0)} earned)")
        # per-tenant hedge buckets (docs/TENANCY.md): each tenant's
        # bucket is bound by the same burst — isolation means no
        # tenant can borrow another's hedge headroom
        for tenant in sorted(d.get("hedge_budget_by_tenant", {})):
            bucket = d["hedge_budget_by_tenant"][tenant]
            if _bucket_over(bucket, hedge_burst):
                return (f"{path or 'report'}: tenant {tenant!r} "
                        f"hedge budget overspent ({bucket['spent']}"
                        f" > burst {hedge_burst} + ratio x "
                        f"{bucket.get('earned', 0)} earned)")
        counters = d["counters"]
        if not disabled and counters.get(
                "retries_scheduled", 0) != spent:
            return (f"{path or 'report'}: retries_scheduled="
                    f"{counters.get('retries_scheduled', 0)} but "
                    f"buckets spent {spent} (amplification outside "
                    "the budget path)")
        if not disabled and counters.get(
                "retries_suppressed", 0) != suppressed:
            return (f"{path or 'report'}: retries_suppressed="
                    f"{counters.get('retries_suppressed', 0)} but "
                    f"buckets suppressed {suppressed}")
    return None


def _check_tenant_accounting(ctx: InvariantContext) -> Optional[str]:
    """Tenancy conservation (docs/TENANCY.md): every fresh arrival a
    tenanted sim booked at admission (admitted + quota sheds + token
    sheds) corresponds to exactly one distinct base request id in the
    completion log — quota enforcement may refuse work but never
    lose or invent it."""
    for path, d in _sim_reports(ctx.report):
        ten = d.get("tenancy")
        if not isinstance(ten, dict) or "tenants" not in ten:
            continue
        tallies: Dict[str, set] = {}
        for e in d["completions"]:
            if not isinstance(e, dict):
                continue
            name = e.get("tenant", "") or "default"
            tallies.setdefault(name, set()).add(
                str(e.get("request_id")).split("~", 1)[0])
        for name in sorted(ten["tenants"]):
            t = ten["tenants"][name]
            booked = (t.get("admitted", 0) + t.get("quota_shed", 0)
                      + t.get("token_shed", 0))
            seen = len(tallies.get(name, ()))
            if booked != seen:
                return (f"{path or 'report'}: tenant {name!r} "
                        f"booked {booked} fresh arrivals (admitted "
                        f"+ quota/token sheds) but {seen} distinct "
                        "base ids reached a terminal outcome")
    return None


def _check_no_corruption_escape(ctx: InvariantContext
                                ) -> Optional[str]:
    """No corruption escapes after detection (docs/SDC.md): once a
    replica's defective chip is detected (its entry in the integrity
    section's ``detections``), no LATER completion it produced may
    carry a corrupted payload that was served uncaught. Earlier
    escapes are the detection latency the audit_frac knob prices;
    later ones would mean quarantine failed to contain the chip."""
    for path, d in _sim_reports(ctx.report):
        integ = d.get("integrity")
        if not isinstance(integ, dict):
            continue
        detected = {det["replica"]: det["at_s"]
                    for det in integ.get("detections", ())
                    if isinstance(det, dict)}
        if not detected:
            continue
        for e in d["completions"]:
            if not isinstance(e, dict) or not e.get("corrupted"):
                continue
            if e.get("sdc_caught"):
                continue
            rid = e.get("replica")
            at = detected.get(rid)
            if at is not None and e["finish_s"] > at:
                return (f"{path or 'report'}: replica {rid} served "
                        f"corrupted {e['request_id']!r} at "
                        f"{e['finish_s']} — AFTER its detection at "
                        f"{at} (containment failed)")
    return None


def _check_recovery(ctx: InvariantContext) -> Optional[str]:
    # an SDC quarantine (docs/SDC.md) is a TERMINAL capacity loss:
    # the defective chip has no heal event, so the overload layer's
    # return-to-healthy contract (breakers closed, brownout
    # released) no longer applies — the survivors may legitimately
    # still be saturated at quiesce. Every other fault is windowed
    # and must heal.
    sdc_quarantined = any(
        d["integrity"].get("detections")
        for _, d in _walk(ctx.report)
        if isinstance(d.get("integrity"), dict))
    if sdc_quarantined:
        return None
    for path, d in _walk(ctx.report):
        if "brownout" in d and isinstance(d["brownout"], dict):
            b = d["brownout"]
            if b.get("enabled") and b.get("level", 0) != 0:
                return (f"{path or 'report'}: brownout still at "
                        f"level {b['level']} after quiesce")
        if "breakers" in d and isinstance(d["breakers"], dict):
            for name in sorted(d["breakers"]):
                st = d["breakers"][name].get("state")
                if st == "open":
                    return (f"{path or 'report'}: breaker "
                            f"{name!r} still open after quiesce")
    return None


def _divergence_detail(a: dict, b: dict) -> str:
    div = replaycheck.first_divergence(
        replaycheck.event_stream(a), replaycheck.event_stream(b))
    if div is None:
        return "reports differ but event streams match"
    return (f"first divergent event #{div.index} (stream "
            f"{div.stream}): "
            + json.dumps({"a": div.a, "b": div.b},
                         sort_keys=True, default=str)[:400])


def _check_replay(ctx: InvariantContext) -> Optional[str]:
    if not ctx.can_rerun:
        return None
    again = ctx.rerun(None)
    a = json.dumps(ctx.report, sort_keys=True, default=str)
    b = json.dumps(again, sort_keys=True, default=str)
    if a == b:
        return None
    return "replay diverged: " + _divergence_detail(
        ctx.report, again)


def _check_event_core(ctx: InvariantContext) -> Optional[str]:
    if not ctx.can_rerun:
        return None
    off = ctx.rerun(False)
    a = json.dumps(ctx.report, sort_keys=True, default=str)
    b = json.dumps(off, sort_keys=True, default=str)
    if a == b:
        return None
    return ("event-core on/off reports differ: "
            + _divergence_detail(ctx.report, off))


def _check_selftest_bug(ctx: InvariantContext) -> Optional[str]:
    """The DELIBERATELY BROKEN invariant behind ``chaos fuzz
    --inject-invariant-bug`` (the `--inject-entropy-bug` idiom): it
    flags a perfectly legal composition — a slow_replica window
    overlapping a replica_preempt window — so the self-test can
    prove the fuzzer finds it and the shrinker reduces the spec to
    exactly that fault pair."""
    slows = [f for f in ctx.spec.faults
             if f.kind == "slow_replica"]
    preempts = [f for f in ctx.spec.faults
                if f.kind == "replica_preempt"]
    for a in slows:
        for b in preempts:
            if (a.start_frac < b.end_frac
                    and b.start_frac < a.end_frac):
                return ("planted bug: slow_replica "
                        f"[{a.start_frac}, {a.end_frac}] overlaps "
                        f"replica_preempt [{b.start_frac}, "
                        f"{b.end_frac}]")
    # the SDC flavor of the same plant: an sdc_chip composed with
    # any replica_preempt — the pair the shrinker self-test must
    # reduce a 4-fault SDC schedule down to (docs/SDC.md)
    sdcs = [f for f in ctx.spec.faults if f.kind == "sdc_chip"]
    if sdcs and preempts:
        a, b = sdcs[0], preempts[0]
        return ("planted bug: sdc_chip at "
                f"{a.start_frac} composed with replica_preempt "
                f"[{b.start_frac}, {b.end_frac}]")
    return None


CATALOG: Dict[str, Invariant] = {inv.name: inv for inv in (
    Invariant("verdict-ok",
              "the report's own verdict is green",
              _check_verdict),
    Invariant("no-lost-work",
              "every traced request reaches exactly one terminal "
              "outcome; no attempt id is double-logged",
              _check_no_lost_work),
    Invariant("ledger-clean",
              "training ledgers verify; steps are lost only under "
              "a composed train_kill",
              _check_ledger),
    Invariant("containment",
              "retry/hedge token-bucket arithmetic holds and the "
              "counters reconcile with the buckets",
              _check_containment),
    Invariant("tenant-accounting",
              "per-tenant admission books (admitted + quota/token "
              "sheds) reconcile with distinct completed base ids",
              _check_tenant_accounting),
    Invariant("no-corruption-escapes",
              "after a replica's SDC detection, no later corrupted "
              "completion it produced is served uncaught",
              _check_no_corruption_escape),
    Invariant("recovery",
              "after quiesce no breaker is open and brownout is "
              "back at level 0",
              _check_recovery),
    Invariant("replay-identical",
              "a second run of (spec, seed) is byte-identical "
              "(divergences named by the replaycheck bisector)",
              _check_replay, needs_rerun=True),
    Invariant("event-core-equality",
              "event-heap core on/off produces the identical "
              "report",
              _check_event_core, needs_rerun=True),
    Invariant("fuzz-selftest-bug",
              "DELIBERATELY BROKEN self-test invariant: flags any "
              "overlapping slow_replica x replica_preempt "
              "composition (chaos fuzz --inject-invariant-bug)",
              _check_selftest_bug, universal=False),
)}

# Checked on every fuzzer run regardless of what the spec declares.
UNIVERSAL: Tuple[str, ...] = tuple(
    inv.name for inv in CATALOG.values() if inv.universal)


def check(spec: ScenarioSpec, report: dict,
          rerun: Optional[Callable[[Optional[bool]], dict]] = None,
          names: Optional[Tuple[str, ...]] = None) -> List[dict]:
    """Evaluate the named invariants (default: the spec's declared
    set plus UNIVERSAL when the context can rerun) and return the
    violations, each ``{"invariant": name, "detail": str}``, in
    catalog order — deterministic for byte-identical fuzz reports."""
    ctx = InvariantContext(spec, report, rerun)
    if names is None:
        names = tuple(dict.fromkeys(
            (UNIVERSAL if rerun is not None else ())
            + tuple(spec.invariants)))
    out: List[dict] = []
    for name in names:
        inv = CATALOG.get(name)
        if inv is None:
            raise ValueError(
                f"unknown invariant {name!r}; known: "
                f"{', '.join(sorted(CATALOG))}")
        detail = inv.check(ctx)
        if detail is not None:
            out.append({"invariant": name, "detail": detail})
    return out
