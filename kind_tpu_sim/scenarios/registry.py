"""The scenario registry — single source of truth for every named
scenario (docs/FUZZ.md).

Replaces the hand-maintained scenario lists: `chaos run`'s listing,
`chaos soak`'s pick pool, and `analysis replay`'s REPLAY_TARGETS all
derive from here, so a newly added scenario cannot be silently
missing from any of them (registry_problems() is the machine check).

Every scenario in ``chaos.SCENARIOS`` is re-expressed as a
:class:`ScenarioSpec`: the ``_LEGACY`` table declares its fault
kinds, its named invariants (the bespoke assertions, as catalog
entries), and whether its report is a pure function of (config,
seed) (``replayable`` — what replaycheck targets). The original
scenario functions stay the executors, so every legacy name keeps
its byte-identical report; purely declarative specs (the fuzzer's
output, pinned repros) run through :func:`spec.run_spec`.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

from kind_tpu_sim.scenarios.spec import ScenarioSpec, run_spec

# Declarative metadata of the hand-written scenarios in chaos.py:
# name -> (fault kinds exercised, named invariants their bespoke
# assertions map onto, replayable). needs_jax/slow stay declared on
# the chaos.Scenario and are copied into the spec at build time —
# one owner per fact.
_VERDICT = ("verdict-ok",)
_FLEETV = ("verdict-ok", "no-lost-work")
_LEGACY: Dict[str, tuple] = {
    "flaky-exec": (("cmd_transient",), _VERDICT, True),
    "worker-crash-grid": (("worker_crash",), _VERDICT, False),
    "worker-hang-grid": (("worker_hang",), _VERDICT, False),
    "device-flap": (("device_flap",), _VERDICT, True),
    "node-flap": (("node_kill", "node_restart"), _VERDICT, True),
    "preempt-train": (("preempt_sigterm",), _VERDICT, False),
    "serving-slot-failure": (("slot_failure",), _VERDICT, False),
    "fleet-flaky-replica": (("replica_flap",), _FLEETV, True),
    "fleet-preemption": (("replica_preempt",), _VERDICT, False),
    "sched-node-drain": (("node_drain",), _FLEETV, True),
    "sched-preemption-priority": ((), _FLEETV, True),
    "gray-straggler-grid": (("straggler_worker",), _VERDICT, False),
    "gray-slow-replica": (("slow_replica",), _FLEETV, True),
    "gray-degraded-ici": (("degraded_link",), _FLEETV, True),
    "globe-zone-loss": (("zone_loss",), _FLEETV, True),
    "globe-herd-failover": (("herd_failover",), _FLEETV, True),
    "globe-dcn-degrade": (("dcn_degrade", "cell_drain"), _FLEETV,
                          True),
    "overload-surge": (
        ("demand_surge",),
        ("verdict-ok", "no-lost-work", "containment"), True),
    "retry-storm": (
        ("retry_storm", "replica_preempt"),
        ("verdict-ok", "no-lost-work", "containment"), True),
    "train-preempt-economics": (
        ("train_preempt", "train_kill"),
        ("verdict-ok", "ledger-clean"), True),
    "train-mixed-soak": (
        ("node_drain", "node_fail", "replica_preempt"),
        ("verdict-ok", "no-lost-work", "ledger-clean"), True),
    "train-globe-spot": (
        ("zone_loss",),
        ("verdict-ok", "no-lost-work", "ledger-clean"), True),
    "disagg-pool-loss": (
        ("prefill_pool_loss", "kv_transfer_degrade"), _FLEETV, True),
    "tenant-noisy-neighbor": (
        ("noisy_neighbor",), _FLEETV, True),
    "zoo-swap-storm": (
        ("model_swap_storm",), _FLEETV, True),
    "sdc-training-bisect": (
        ("sdc_chip",),
        ("verdict-ok", "ledger-clean", "no-corruption-escapes"),
        True),
    "sdc-serving-audit": (
        ("sdc_chip",),
        ("verdict-ok", "no-corruption-escapes"), True),
    "correlated-rack-loss": (
        ("correlated_domain_fault",), _FLEETV, True),
}

_SPECS: Optional[Dict[str, ScenarioSpec]] = None


def _build() -> Dict[str, ScenarioSpec]:
    from kind_tpu_sim import chaos

    specs: Dict[str, ScenarioSpec] = {}
    for name in sorted(chaos.SCENARIOS):
        scn = chaos.SCENARIOS[name]
        kinds, invs, replayable = _LEGACY.get(
            name, ((), _VERDICT, False))
        specs[name] = ScenarioSpec(
            name=name,
            description=scn.description,
            kind="legacy",
            fault_kinds=tuple(kinds),
            invariants=tuple(invs),
            needs_jax=scn.needs_jax,
            slow=scn.slow,
            replayable=bool(replayable and not scn.slow),
        )
    return specs


def specs() -> Dict[str, ScenarioSpec]:
    """Every registered scenario, by name (cached)."""
    global _SPECS
    if _SPECS is None:
        _SPECS = _build()
    return _SPECS


def get(name: str) -> ScenarioSpec:
    table = specs()
    if name not in table:
        raise ValueError(
            f"unknown scenario {name!r}; known: "
            f"{', '.join(sorted(table))}")
    return table[name]


def names(include_slow: bool = True) -> List[str]:
    return sorted(n for n, s in specs().items()
                  if include_slow or not s.slow)


def soak_names(include_slow: bool = False) -> List[str]:
    """The `chaos soak` pick pool — sorted so the seeded iteration
    stream is a pure function of the registry contents."""
    return names(include_slow=include_slow)


def replayable_names() -> List[str]:
    """The scenario names replaycheck targets (reports that are
    pure functions of (config, seed))."""
    return sorted(n for n, s in specs().items() if s.replayable)


def executor(name: str) -> Callable[[int], dict]:
    """The callable that runs scenario ``name`` at a seed: the
    original chaos.py function for legacy entries, the spec
    compiler for declarative ones."""
    spec = get(name)
    if spec.kind == "legacy":
        from kind_tpu_sim import chaos

        return chaos.SCENARIOS[name].fn
    return lambda seed: run_spec(spec, seed=seed)


def evaluate(name_or_spec, report: dict) -> List[dict]:
    """Check a scenario's declared invariants against one of its
    reports (no reruns — the rerun-needing invariants pass
    vacuously here; the fuzzer checks those live)."""
    from kind_tpu_sim.scenarios import invariants

    spec = (name_or_spec if isinstance(name_or_spec, ScenarioSpec)
            else get(name_or_spec))
    return invariants.check(spec, report,
                            names=tuple(spec.invariants))


def listing() -> List[dict]:
    """The `chaos run --list` surface: every scenario's declarative
    row, sorted by name (JSON-stable)."""
    return [
        {
            "name": s.name,
            "description": s.description,
            "kind": s.kind,
            "fault_kinds": list(s.all_fault_kinds()),
            "invariants": list(s.invariants),
            "needs_jax": s.needs_jax,
            "slow": s.slow,
            "replayable": s.replayable,
        }
        for _, s in sorted(specs().items())
    ]


def registry_problems() -> List[str]:
    """Cross-checks keeping the registry honest (wired into
    `analysis lint` + tests): every chaos.SCENARIOS entry must
    carry declarative metadata, every metadata row must name a real
    scenario, and every declared invariant must exist in the
    catalog."""
    from kind_tpu_sim import chaos
    from kind_tpu_sim.scenarios import invariants

    problems: List[str] = []
    for name in sorted(chaos.SCENARIOS):
        if name not in _LEGACY:
            problems.append(
                f"scenario {name!r} has no registry metadata "
                "(kind_tpu_sim/scenarios/registry.py _LEGACY)")
    for name in sorted(_LEGACY):
        if name not in chaos.SCENARIOS:
            problems.append(
                f"registry metadata names unknown scenario "
                f"{name!r}")
        kinds, invs, _ = _LEGACY[name]
        for kind in kinds:
            if kind not in chaos.FAULT_KINDS:
                problems.append(
                    f"scenario {name!r} metadata names unknown "
                    f"fault kind {kind!r}")
        for inv in invs:
            if inv not in invariants.CATALOG:
                problems.append(
                    f"scenario {name!r} declares unknown "
                    f"invariant {inv!r}")
    return problems
