"""Seeded chaos fuzzer (docs/FUZZ.md).

``fuzz(budget, seed)`` draws ``budget`` composed scenarios — 2-4
concurrent fault kinds with jittered overlap windows, mixed serving
and training tenants, fleet and globe topologies — runs each under
the full universal invariant set (including the replay and
event-core rerun checks), and auto-shrinks every violation to a
minimal repro spec (scenarios/shrink.py).

Everything is a pure function of ``(budget, seed, max_faults)``:
every random draw comes from ``random.Random(zlib.crc32(...))``
streams, so the same seed produces the byte-identical fuzz report —
the property `chaos fuzz` CI runs pin. Wall-clock timings are only
added when the caller passes a ``timer`` (bench does; the CLI does
not), keeping the canonical report timer-free.
"""

from __future__ import annotations

import dataclasses
import random
import zlib
from typing import Dict, List, Optional

from kind_tpu_sim.chaos import FAULT_SCHEMAS, draw_param
from kind_tpu_sim.scenarios import invariants, shrink
from kind_tpu_sim.scenarios.spec import (FaultWindow, ScenarioSpec,
                                         TopologySpec, WorkloadDims,
                                         run_spec, spec_problems)

# Fault windows live inside [START_LO, END_CAP] of the trace span:
# starts jittered over the first half so 2-4 windows overlap often,
# everything healed by 75% so the recovery invariant (breakers
# closed, brownout released) has quiesce room before the sim drains.
_START = (0.15, 0.5)
_DURATION = (0.1, 0.25)
_END_CAP = 0.75

_PROCESSES = ("poisson", "bursty", "diurnal")


def _eligible_kinds(topo: TopologySpec, training_gangs: int,
                    overload: bool) -> List[str]:
    """The fault kinds a drawn topology can legally compose
    (mirrors spec_problems — the fuzzer emits valid specs by
    construction)."""
    out = []
    for kind in sorted(FAULT_SCHEMAS):
        schema = FAULT_SCHEMAS[kind]
        if not schema.fuzzable or topo.kind not in schema.scopes:
            continue
        if "sched" in schema.needs and (topo.kind == "fleet"
                                        and not topo.sched):
            continue
        if "training" in schema.needs and training_gangs <= 0:
            continue
        if "overload" in schema.needs and not overload:
            continue
        if "disagg" in schema.needs and not getattr(
                topo, "disagg", False):
            continue
        if "tenancy" in schema.needs and not getattr(
                topo, "tenancy", False):
            continue
        if "zoo" in schema.needs:
            # zoo kinds never enter the shared pool: draw_spec adds
            # them from the dedicated zoo stream, so the base kind
            # draws — and every pre-zoo fuzz report, zoo-flavored
            # or not — keep their bytes
            continue
        if "sdc" in schema.needs:
            # SDC kinds ride their own dedicated stream too (the
            # zoo precedent): the shared pool never sees them, so
            # every pre-SDC fuzz report keeps its bytes
            continue
        out.append(kind)
    return out


def draw_spec(seed: int, index: int,
              max_faults: int = 4) -> ScenarioSpec:
    """Draw composed scenario ``index`` of the fuzz stream ``seed``
    — a pure function of its arguments."""
    rng = random.Random(zlib.crc32(
        f"fuzz:{seed}:{index}:{max_faults}".encode()))
    if rng.random() < 0.7:
        topo = TopologySpec(kind="fleet",
                            replicas=rng.randint(2, 3),
                            sched=rng.random() < 0.6)
    else:
        topo = TopologySpec(kind="globe",
                            replicas=2,
                            zones=rng.randint(2, 3),
                            cells_per_zone=rng.randint(1, 2))
    # disagg comes from a SEPARATE stream so every existing draw
    # (and thus every pre-disagg fuzz report for non-disagg specs)
    # stays byte-identical — pulling this bit from `rng` would
    # shift all downstream draws
    if topo.kind == "fleet" and not topo.sched:
        disagg_rng = random.Random(zlib.crc32(
            f"fuzz:disagg:{seed}:{index}".encode()))
        if disagg_rng.random() < 0.4:
            topo = dataclasses.replace(topo, disagg=True)
    # tenancy rides its own stream too (the disagg precedent): the
    # shared `rng` draw sequence — and with it every pre-tenancy
    # fuzz report for untenanted specs — stays byte-identical
    if topo.kind == "fleet":
        tenant_rng = random.Random(zlib.crc32(
            f"fuzz:tenant:{seed}:{index}".encode()))
        if tenant_rng.random() < 0.35:
            topo = dataclasses.replace(topo, tenancy=True)
    # the model zoo rides its own stream as well (the disagg /
    # tenancy precedent): every pre-zoo fuzz report for unzooed
    # specs keeps its bytes. Zoo fleets are analytic (spec fleets
    # pin generations directly; sched x zoo is a FleetConfig-level
    # combination) and unified (no disagg).
    zoo_rng = None
    if not topo.disagg and not (topo.kind == "fleet" and topo.sched):
        zoo_rng = random.Random(zlib.crc32(
            f"fuzz:zoo:{seed}:{index}".encode()))
        if zoo_rng.random() < 0.3:
            topo = dataclasses.replace(topo, zoo=True)
    overload = rng.random() < 0.7
    training_gangs = 0
    if topo.kind == "fleet" and topo.sched:
        training_gangs = rng.randint(0, 1)
    workload = WorkloadDims(
        process=rng.choice(_PROCESSES),
        rps=round(rng.uniform(20.0, 45.0), 1),
        n_requests=rng.randint(80, 160))

    pool = _eligible_kinds(topo, training_gangs, overload)
    n_faults = rng.randint(2, max(2, min(max_faults, len(pool))))
    kinds: List[str] = []
    for _ in range(min(n_faults, len(pool))):
        kind = rng.choice(pool)
        kinds.append(kind)
        if FAULT_SCHEMAS[kind].exclusive:
            pool = [k for k in pool
                    if not FAULT_SCHEMAS[k].exclusive]
        else:
            pool = [k for k in pool if k != kind]
        if not pool:
            break

    faults = []
    for kind in kinds:
        start = round(rng.uniform(*_START), 3)
        end = round(min(_END_CAP,
                        start + rng.uniform(*_DURATION)), 3)
        faults.append(FaultWindow(
            kind=kind, start_frac=start, end_frac=end,
            target=rng.randint(0, 7),
            param=draw_param(kind, rng)))
    # zoo faults ride the zoo stream end to end (window, target,
    # and magnitude included): the shared `rng` never sees them,
    # so the base fault draws above are byte-identical whether the
    # topology is zoo-flavored or not
    if topo.zoo and zoo_rng is not None:
        has_exclusive = any(FAULT_SCHEMAS[f.kind].exclusive
                            for f in faults)
        for kind in sorted(FAULT_SCHEMAS):
            schema = FAULT_SCHEMAS[kind]
            if "zoo" not in schema.needs or not schema.fuzzable:
                continue
            if topo.kind not in schema.scopes:
                continue
            if schema.exclusive and has_exclusive:
                continue
            if zoo_rng.random() < 0.5:
                start = round(zoo_rng.uniform(*_START), 3)
                end = round(min(_END_CAP,
                                start + zoo_rng.uniform(*_DURATION)),
                            3)
                faults.append(FaultWindow(
                    kind=kind, start_frac=start, end_frac=end,
                    target=zoo_rng.randint(0, 7),
                    param=draw_param(kind, zoo_rng)))
                if schema.exclusive:
                    has_exclusive = True
    # SDC faults ride a dedicated stream as well (docs/SDC.md): the
    # shared `rng` never sees them, so every pre-SDC fuzz report —
    # corruption-flavored or not — keeps its bytes. Defective chips
    # live on unified, un-zooed fleets (the audit lane needs
    # same-model duplicate compute); correlated domain faults
    # additionally need the rack-aware scheduler.
    if topo.kind == "fleet" and not topo.disagg and not topo.zoo:
        sdc_rng = random.Random(zlib.crc32(
            f"fuzz:sdc:{seed}:{index}".encode()))
        if sdc_rng.random() < 0.5:
            has_exclusive = any(FAULT_SCHEMAS[f.kind].exclusive
                                for f in faults)
            drew_sdc_chip = False
            for kind in sorted(FAULT_SCHEMAS):
                schema = FAULT_SCHEMAS[kind]
                if "sdc" not in schema.needs or not schema.fuzzable:
                    continue
                if "sched" in schema.needs and not topo.sched:
                    continue
                if schema.exclusive and has_exclusive:
                    continue
                if sdc_rng.random() < 0.7:
                    start = round(sdc_rng.uniform(*_START), 3)
                    end = round(min(_END_CAP,
                                    start
                                    + sdc_rng.uniform(*_DURATION)),
                                3)
                    faults.append(FaultWindow(
                        kind=kind, start_frac=start, end_frac=end,
                        target=sdc_rng.randint(0, 7),
                        param=draw_param(kind, sdc_rng)))
                    if schema.exclusive:
                        has_exclusive = True
                    if kind == "sdc_chip":
                        drew_sdc_chip = True
            # a corruption-flavored draw sometimes buys the audit
            # lane too, so the fuzzer exercises both detection
            # (audits on) and tolerated escape (audits off) under
            # the no-corruption-escapes invariant
            if drew_sdc_chip and sdc_rng.random() < 0.5:
                topo = dataclasses.replace(
                    topo,
                    audit_frac=round(sdc_rng.uniform(0.2, 0.6), 3))
    # window order is part of the drawn identity; sort for a stable
    # spec no matter the draw order
    faults.sort(key=lambda f: (f.start_frac, f.kind, f.target))

    return ScenarioSpec(
        name=f"fuzz-{seed}-{index}",
        description="fuzzer-composed scenario",
        kind="spec",
        seed=rng.randint(0, 10**6),
        topology=topo,
        workload=workload,
        faults=tuple(faults),
        training_gangs=training_gangs,
        overload=overload)


def fuzz(budget: int, seed: int, max_faults: int = 4,
         inject_bug: bool = False, emit_specs: bool = False,
         timer=None) -> Dict[str, object]:
    """Run the fuzz campaign: ``budget`` drawn scenarios, each
    checked against the universal invariant set (plus the planted
    ``fuzz-selftest-bug`` when ``inject_bug`` — the self-test that
    proves the find-and-shrink loop works). Violations are shrunk
    to minimal repro specs in ``report["shrunk"]``."""
    names = tuple(invariants.UNIVERSAL)
    if inject_bug:
        names = names + ("fuzz-selftest-bug",)
    runs: List[dict] = []
    shrunk: List[dict] = []
    t0 = timer() if timer is not None else 0.0
    check_s = 0.0
    for index in range(budget):
        spec = draw_spec(seed, index, max_faults=max_faults)
        problems = spec_problems(spec)
        if problems:   # unreachable by construction; belt-and-braces
            runs.append({"index": index, "name": spec.name,
                         "ok": False, "violations": [],
                         "invalid": problems})
            continue
        report = run_spec(spec)
        c0 = timer() if timer is not None else 0.0
        violations = invariants.check(
            spec, report,
            rerun=lambda ec, s=spec: run_spec(s, event_core=ec),
            names=names)
        if timer is not None:
            check_s += timer() - c0
        entry = {
            "index": index,
            "name": spec.name,
            "topology": spec.topology.kind,
            "fault_kinds": list(spec.all_fault_kinds()),
            "ok": not violations,
            "violations": violations,
        }
        if emit_specs or violations:
            entry["spec"] = spec.as_dict()
        runs.append(entry)
        if violations:
            shrunk.append(shrink.shrink(
                spec, tuple(v["invariant"] for v in violations)))
    n_violating = sum(1 for r in runs if not r["ok"])
    found_planted = any(
        v["invariant"] == "fuzz-selftest-bug"
        for r in runs for v in r["violations"])
    other = sum(1 for r in runs for v in r["violations"]
                if v["invariant"] != "fuzz-selftest-bug")
    report: Dict[str, object] = {
        "budget": budget,
        "seed": seed,
        "max_faults": max_faults,
        "inject_bug": inject_bug,
        "runs": runs,
        "violating_runs": n_violating,
        "shrunk": shrunk,
        # plain campaign: green means nothing violated. self-test
        # campaign: green means the planted bug WAS found (and
        # nothing real was): the fuzzer proves it can find and
        # shrink before CI trusts its silence
        "ok": ((other == 0 and found_planted) if inject_bug
               else n_violating == 0),
    }
    if inject_bug:
        report["selftest_found"] = found_planted
    if timer is not None:
        elapsed = max(1e-9, timer() - t0)
        report["timings"] = {
            "elapsed_s": round(elapsed, 3),
            "invariant_s": round(check_s, 3),
            "invariant_frac": round(check_s / elapsed, 4),
            "runs_per_s": round(budget / elapsed, 3),
        }
    return report
