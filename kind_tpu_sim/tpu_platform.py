"""Simulated TPU platform identity for JAX (`platform == "tpu"`).

The reference never faces this problem — its pods assert log lines,
not accelerator identity (pods/nvidia-gpu-test-pod.yaml:9). The TPU
sim's BASELINE asks for more: a JAX pod on a simulated node should
*look like* a TPU worker, including `jax.devices()[0].platform`.

What the PJRT probing established (reproduce with
``python tools/probe_pjrt.py``; transcript in docs/PJRT.md):

1. A rename/delegating C shim over jaxlib is impossible: jaxlib ships
   no PJRT C API entry point (``nm -D`` over ``_jax.so`` and
   ``libjax_common.so`` shows no ``GetPjrtApi``) — the CPU client is
   in-process C++ only.
2. ``libtpu.so`` DOES export ``GetPjrtApi`` and its client is named
   "tpu", but client creation requires real hardware: on a
   hardware-less host it fails with ``TPU initialization failed: No
   jellyfish device found``.
3. ``xla_bridge.register_backend_factory("tpu", <cpu factory>)``
   works as an *alias* — ``JAX_PLATFORMS=tpu`` selects it and
   collectives run — but ``Device.platform`` still reads "cpu": the
   attribute is owned by the C++ PJRT client, not the registry name.
4. The nanobind ``Device`` class accepts a class-level property
   override, which closes the remaining gap at the user-facing
   surface.

So the simulation tier is layered (this module): the CPU-backed
"tpu" backend alias (3) plus the ``Device.platform``/``device_kind``
override (4). Deliberately NOT patched: the PJRT *client*'s platform
name, which jax's lowering consults — compilation must keep
targeting the host CPU, otherwise XLA would emit TPU-only ops for
hardware that isn't there. The identity is skin-deep by design and
honest about it.
"""

from __future__ import annotations

import os

_ACTIVATED = False

SIMULATED_DEVICE_KIND = "TPU v5 lite (simulated)"


def check_compat() -> list:
    """Names of jax-internal surfaces this shim needs that are MISSING
    from the installed jax (empty list = compatible).

    The shim leans on jax 0.9.0 internals; a jax bump that renames
    any of them must fail LOUDLY here (activate raises with the
    missing names and the validated pin) instead of silently leaving
    pods with a cpu identity — the failure mode VERDICT r2 flagged.
    """
    from kind_tpu_sim.utils.jax_compat import (
        jaxlib_extension, jaxlib_extension_name)

    missing = []
    _jax = jaxlib_extension()
    if _jax is None:
        return ["jaxlib._jax (module; jaxlib.xla_extension fallback "
                "also missing)"]
    ext = jaxlib_extension_name()
    for attr in ("get_tfrt_cpu_client", "Device"):
        if not hasattr(_jax, attr):
            missing.append(f"{ext}.{attr}")
    if hasattr(_jax, "Device"):
        # pre-activation these are nanobind descriptors (not Python
        # `property`); only their existence is checkable without
        # mutating the class
        for prop in ("platform", "device_kind"):
            if getattr(_jax.Device, prop, None) is None:
                missing.append(f"{ext}.Device.{prop}")
    try:
        from jax._src import xla_bridge as xb
    except ImportError:
        return missing + ["jax._src.xla_bridge (module)"]
    if not isinstance(getattr(xb, "_backend_factories", None), dict):
        missing.append("jax._src.xla_bridge._backend_factories (dict)")
    if not callable(getattr(xb, "register_backend_factory", None)):
        missing.append(
            "jax._src.xla_bridge.register_backend_factory")
    return missing


def activate(device_kind: str | None = None) -> None:
    """Make JAX's CPU devices identify as simulated TPU chips.

    Idempotent. Call before or after jax initialization; with
    ``JAX_PLATFORMS=tpu`` set before the first jax use, the CPU-backed
    alias backend is selected under the "tpu" name too.
    """
    global _ACTIVATED
    if _ACTIVATED:
        return
    incompat = check_compat()
    if incompat:
        import jax

        raise RuntimeError(
            "kind-tpu-sim platform shim: installed jax "
            f"{jax.__version__} no longer exposes "
            f"{', '.join(incompat)}; the shim is validated against "
            f"{POD_JAX_REQUIREMENT} (kind_tpu_sim/tpu_platform.py)")
    from jax._src import xla_bridge as xb

    from kind_tpu_sim.utils.jax_compat import jaxlib_extension

    _jax = jaxlib_extension()

    kind = device_kind or os.environ.get(
        "TPU_SIM_DEVICE_KIND", SIMULATED_DEVICE_KIND)

    # (3) "tpu" backend alias over the CPU client, unless a real tpu
    # factory (libtpu/plugin) is already registered. Direct attribute
    # access on purpose: if jax renames the registry, fail loudly
    # instead of silently clobbering a real TPU backend.
    if "tpu" not in xb._backend_factories:
        def _cpu_as_tpu():
            return _jax.get_tfrt_cpu_client(asynchronous=True)

        xb.register_backend_factory("tpu", _cpu_as_tpu, priority=300)

    # (4) user-facing identity override, CPU devices only — a real
    # accelerator (or the axon tunnel) keeps its own identity.
    orig_platform = _jax.Device.platform
    orig_kind = _jax.Device.device_kind
    _jax.Device.platform = property(
        lambda self: "tpu"
        if orig_platform.__get__(self) == "cpu"
        else orig_platform.__get__(self))
    _jax.Device.device_kind = property(
        lambda self: kind
        if orig_platform.__get__(self) == "cpu"
        else orig_kind.__get__(self))
    _ACTIVATED = True


# The self-contained copy of activate() that pod manifests embed
# (pods pip-install jax only; kind_tpu_sim is not on their path).
# The shim leans on jax internals (jaxlib._jax, get_tfrt_cpu_client),
# so pods embedding it must pin the jax version it was validated
# against (POD_JAX_REQUIREMENT).
POD_JAX_REQUIREMENT = "jax==0.9.0"

POD_SNIPPET = f'''\
def _sim_tpu_platform():
    """kind-tpu-sim platform shim (kind_tpu_sim/tpu_platform.py)."""
    try:
        import jaxlib._jax as _jax
    except ImportError:  # pre-0.5 jaxlib layout
        import jaxlib.xla_extension as _jax
    from jax._src import xla_bridge as xb

    if "tpu" not in xb._backend_factories:
        xb.register_backend_factory(
            "tpu", lambda: _jax.get_tfrt_cpu_client(asynchronous=True),
            priority=300)
    orig_platform = _jax.Device.platform
    orig_kind = _jax.Device.device_kind
    _jax.Device.platform = property(
        lambda self: "tpu"
        if orig_platform.__get__(self) == "cpu"
        else orig_platform.__get__(self))
    _jax.Device.device_kind = property(
        lambda self: "{SIMULATED_DEVICE_KIND}"
        if orig_platform.__get__(self) == "cpu"
        else orig_kind.__get__(self))


_sim_tpu_platform()
'''
