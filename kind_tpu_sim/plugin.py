"""Device-plugin image build and deployment (layer L4).

Three vendors, two build strategies:

* ``tpu`` — the in-repo native C++ plugin under ``plugin/`` (this repo's
  equivalent of the external Go plugins the reference clones; see
  SURVEY.md §2 "native components").  Built locally from source with no
  network access needed.
* ``rocm`` / ``nvidia`` — behavioral parity with the reference
  (kind-gpu-sim.sh:180-228): clone the real vendor plugin repo, rewrite
  its base images to rate-limit-free mirrors, build, and deliver.

Delivery follows the reference's two paths: registry push for docker,
``save`` + ``kind load image-archive`` for podman (sh:195-198,203).
"""

from __future__ import annotations

import logging
import os
import pathlib
from typing import Tuple

from kind_tpu_sim import manifests
from kind_tpu_sim.cluster import ClusterManager
from kind_tpu_sim.config import SimConfig
from kind_tpu_sim.registry import LocalRegistry
from kind_tpu_sim.runtime import ContainerRuntime, kind, kubectl

log = logging.getLogger("kind-tpu-sim")

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent

NVIDIA_PLUGIN_REPO = "https://github.com/NVIDIA/k8s-device-plugin.git"
NVIDIA_PLUGIN_TAG = "v0.18.2"   # pin kept from kind-gpu-sim.sh:187
ROCM_PLUGIN_REPO = (
    "https://github.com/RadeonOpenCompute/k8s-device-plugin.git"
)

# base-image rewrites for the cloned vendor Dockerfiles
# (kind-gpu-sim.sh:145-178, reimplemented as literal line rewrites)
FROM_REWRITES = {
    "FROM redhat/ubi9-minimal":
        "FROM registry.access.redhat.com/ubi9/ubi-minimal",
    "FROM public.ecr.aws/ubi9/ubi-minimal":
        "FROM registry.access.redhat.com/ubi9/ubi-minimal",
    "FROM registry.access.redhat.com/ubi9/ubi9-minimal":
        "FROM registry.access.redhat.com/ubi9/ubi-minimal",
    "FROM alpine:":
        "FROM public.ecr.aws/docker/library/alpine:",
    "FROM docker.io/golang:":
        "FROM public.ecr.aws/docker/library/golang:",
    "FROM golang:":
        "FROM public.ecr.aws/docker/library/golang:",
}


def rewrite_base_images(dockerfile: pathlib.Path) -> bool:
    """Rewrite FROM lines to mirror registries; returns True if changed.

    No-op when the Dockerfile is absent (e.g. fake-runtime dry runs where
    the git clone was only recorded, not executed).
    """
    if not dockerfile.exists():
        return False
    text = dockerfile.read_text(encoding="utf-8")
    lines = text.splitlines(keepends=True)
    changed = False
    for i, line in enumerate(lines):
        for old, new in FROM_REWRITES.items():
            if line.startswith(old):
                lines[i] = new + line[len(old):]
                changed = True
                break
    if changed:
        dockerfile.write_text("".join(lines), encoding="utf-8")
    return changed


class PluginManager:
    def __init__(self, cfg: SimConfig, runtime: ContainerRuntime,
                 registry: LocalRegistry, cluster: ClusterManager):
        self.cfg = cfg
        self.rt = runtime
        self.registry = registry
        self.cluster = cluster
        self.ex = runtime.executor

    # -- image naming ---------------------------------------------------

    def image_for(self, vendor: str) -> Tuple[str, str]:
        """(buildable registry ref, in-cluster ref) for a vendor image.

        Podman-built images are delivered by archive under ``localhost/``
        (kind-gpu-sim.sh:195,222,245,288); docker images resolve through
        the local registry mirror.
        """
        short = {
            "tpu": "tpu-device-plugin",
            "rocm": "amdgpu-dp",
            "nvidia": "nvidia-device-plugin",
        }[vendor]
        build_ref = self.registry.image_ref(short)
        cluster_ref = (
            f"localhost/{short}:dev" if self.rt.is_podman else build_ref
        )
        return build_ref, cluster_ref

    # -- build ----------------------------------------------------------

    def build(self, vendor: str) -> str:
        """Build the vendor's plugin image; returns the in-cluster ref."""
        build_ref, cluster_ref = self.image_for(vendor)
        if vendor == "tpu":
            context = str(REPO_ROOT / "plugin")
            dockerfile = os.path.join(context, "Dockerfile")
        elif vendor == "nvidia":
            context = self._clone(
                NVIDIA_PLUGIN_REPO, "k8s-device-plugin-nvidia",
                tag=NVIDIA_PLUGIN_TAG,
            )
            dockerfile = os.path.join(
                context, "deployments", "container", "Dockerfile"
            )
            rewrite_base_images(pathlib.Path(dockerfile))
        elif vendor == "rocm":
            context = self._clone(ROCM_PLUGIN_REPO, "k8s-device-plugin-rocm")
            dockerfile = os.path.join(context, "Dockerfile")
            rewrite_base_images(pathlib.Path(dockerfile))
        else:
            raise ValueError(f"unknown vendor {vendor!r}")

        log.info("building %s device plugin image %s", vendor, build_ref)
        # podman: force docker image format for kind compatibility (sh:192)
        env = {"BUILDAH_FORMAT": "docker"} if self.rt.is_podman else None
        self.ex.run(
            [self.rt.name, "build", "-t", build_ref,
             "-f", dockerfile, context],
            env=env,
        )
        self._deliver(build_ref, cluster_ref)
        return cluster_ref

    def _clone(self, url: str, dirname: str, tag: str | None = None) -> str:
        dest = str(REPO_ROOT / dirname)
        if not os.path.isdir(dest):
            self.ex.run(["git", "clone", url, dest])
        if tag:
            self.ex.run(["git", "-C", dest, "checkout", tag])
        return dest

    def _deliver(self, build_ref: str, cluster_ref: str) -> None:
        if self.rt.is_podman:
            self.rt.run("tag", build_ref, cluster_ref)
            tar = "/tmp/kind-tpu-sim-plugin.tar"
            try:
                self.rt.run("save", cluster_ref, "-o", tar)
                kind(self.ex, "load", "image-archive", tar,
                     "--name", self.cfg.cluster_name)
            finally:
                if os.path.exists(tar):
                    os.unlink(tar)
        else:
            self.rt.run("push", build_ref)

    # -- deploy ---------------------------------------------------------

    def deploy(self, vendor: str, image: str) -> None:
        """Apply the plugin DaemonSet and block until it is rolled out.

        The reference sleeps 5s then waits on pod readiness
        (kind-gpu-sim.sh:278-283); ``rollout status`` subsumes both
        without the fixed sleep.
        """
        if vendor == "tpu":
            ds_yaml = manifests.tpu_plugin_daemonset(self.cfg, image)
            ds_name = "tpu-sim-device-plugin"
        else:
            ds_yaml = manifests.gpu_plugin_daemonset(self.cfg, vendor, image)
            ds_name = {
                "rocm": "amdgpu-device-plugin-daemonset",
                "nvidia": "nvidia-device-plugin-daemonset",
            }[vendor]
        kubectl(self.ex, "apply", "-f", "-", input_text=ds_yaml)
        res = kubectl(
            self.ex, "-n", manifests.PLUGIN_NAMESPACE,
            "rollout", "status", f"daemonset/{ds_name}",
            f"--timeout={self.cfg.plugin_ready_timeout_s}s",
            check=False,
        )
        if not res.ok:
            raise RuntimeError(
                f"{vendor} device plugin DaemonSet not ready within "
                f"{self.cfg.plugin_ready_timeout_s}s: "
                f"{res.stderr.strip() or res.stdout.strip()}"
            )

    def build_and_deploy(self, vendor: str) -> None:
        image = self.build(vendor)
        self.deploy(vendor, image)
