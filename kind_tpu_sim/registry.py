"""Local image registry lifecycle (layer L2).

Behavioral parity with kind-gpu-sim.sh:71-82 (start, idempotent via
running-state inspect, connect to the kind network) and :347-361
(stop/remove with warnings instead of hard failures).
"""

from __future__ import annotations

import logging

from kind_tpu_sim.config import SimConfig
from kind_tpu_sim.runtime import ContainerRuntime

log = logging.getLogger("kind-tpu-sim")


class LocalRegistry:
    def __init__(self, cfg: SimConfig, runtime: ContainerRuntime):
        self.cfg = cfg
        self.rt = runtime

    @property
    def name(self) -> str:
        return self.cfg.registry_name

    def is_running(self) -> bool:
        res = self.rt.try_run(
            "inspect", "-f", "{{.State.Running}}", self.name
        )
        return res.ok and res.stdout.strip() == "true"

    def start(self) -> None:
        log.info("starting local registry on port %d", self.cfg.registry_port)
        if self.is_running():
            log.info("registry %r already running", self.name)
        else:
            self.rt.run(
                "run", "-d", "--restart=always",
                "-p", f"{self.cfg.registry_port}:5000",
                "--name", self.name,
                self.cfg.registry_image,
            )
        self.connect_to_kind_network()

    def connect_to_kind_network(self) -> None:
        # may fail before the kind network exists; harmless (sh:81)
        self.rt.try_run("network", "connect", "kind", self.name)

    def delete(self) -> None:
        log.info("stopping registry %r (if running)", self.name)
        stop = self.rt.try_run("stop", self.name)
        if not stop.ok:
            log.warning("could not stop %r: %s", self.name,
                        stop.stderr.strip() or "not running")
        rm = self.rt.try_run("rm", self.name)
        if not rm.ok:
            log.warning("could not remove %r: %s", self.name,
                        rm.stderr.strip() or "no such container")

    def image_ref(self, image: str, tag: str = "dev") -> str:
        """Registry-qualified image reference for locally-built images."""
        return f"localhost:{self.cfg.registry_port}/{image}:{tag}"
