"""contractlint — the interface-contract sanitizer (static AST pass).

detlint (:mod:`~kind_tpu_sim.analysis.detlint`) guards *determinism*:
same seed, byte-identical report. This tool guards the layer beneath
that promise — the **interfaces** the report is made of. Both classes
of bug it hunts have already bitten this repo once: PR 12's fuzzer
tripped over ``OverloadConfig.as_dict`` silently omitting
``hedge_budget_burst`` (interface drift), and PR 8/9 spent real
effort retiring a ticks-vs-seconds confusion (``eval_every_ticks``).
Example-based tests only catch the paths they cross; contractlint
walks the AST of the whole package and flags the *class*:

=================  ===================================================
``unit``           mixed-unit arithmetic, comparison, or keyword
                   argument passing between identifiers carrying
                   different unit suffixes (``_s``, ``_ms``,
                   ``_ticks``, ``_frac``, ``_bytes``, ``_mb``,
                   ``_tok``, ``_gbps``). ``a_s + b_ticks`` is a bug
                   even when
                   both are floats; multiplication and division are
                   exempt (that's how conversions are written).
``drift``          a ``*Config`` dataclass field that its own
                   ``as_dict`` does not serialize — the
                   ``hedge_budget_burst`` class, caught by
                   construction. Deliberate exclusions carry a
                   per-field waiver with the reason in the source.
``lane``           an :class:`~kind_tpu_sim.fleet.events.EventHeap`
                   ``push`` whose lane argument is not a registered
                   ``LANE_*`` constant (computed lanes break the
                   fixed same-instant total order arrival <
                   completion < chaos < probe < autoscaler <
                   planner), or a ``LANE_*`` (re)definition outside
                   ``fleet/events.py``.
``waiver``         a malformed waiver: missing reason, unknown rule
                   name, or a waiver that matches no finding.
=================  ===================================================

Beyond the per-line rules, :func:`cross_check_problems` holds the
registry bijections (config <-> knob registry <-> ``FAULT_SCHEMAS``
<-> CLI flags <-> lane table), and :func:`collect_report_schema` +
:func:`schema_problems` pin the full report key-space
(``kind_tpu_sim/analysis/report_schema.json``) so report drift is an
explicit reviewed change instead of a replay surprise.

Waivers are per-line and must carry a reason::

    raw = ticks + offset_s  [hash]contractlint: ok(unit) -- grid math

(with ``#`` for ``[hash]``; the comment may also sit alone on the
line directly above). The waiver grammar, finding shape, and file
walk are shared with detlint through
:mod:`~kind_tpu_sim.analysis.lintcore` — *fix or justify*, never
silence.

Run it: ``kind-tpu-sim analysis contract`` (wired into pre-commit and
CI beside ``analysis lint``); the JSON output is sorted-keys and
byte-identical across runs.
"""

from __future__ import annotations

import ast
import json
import pathlib
import re
from typing import Dict, Iterable, List, Optional, Sequence, Tuple, Union

from kind_tpu_sim.analysis import lintcore
from kind_tpu_sim.analysis.lintcore import Finding

RULES: Tuple[str, ...] = ("unit", "drift", "lane", "waiver")

# ---------------------------------------------------------------- unit

# Longest-match-first: `_ms` must win over `_s`, `_ticks` over `_s`.
UNIT_SUFFIXES: Tuple[str, ...] = (
    "_ticks", "_bytes", "_gbps", "_frac", "_tok", "_ms", "_mb",
    "_s",
)


def unit_of_name(name: Optional[str]) -> Optional[str]:
    """The unit a bare identifier carries by suffix convention, or
    None. A name that *is* a suffix (``_s``) carries nothing."""
    if not name:
        return None
    for suffix in UNIT_SUFFIXES:
        if name.endswith(suffix) and len(name) > len(suffix):
            return suffix
    return None


def unit_of_expr(node: ast.AST) -> Optional[str]:
    """Best-effort unit of an expression: names and attributes by
    their own suffix, calls by the called function's suffix (a
    ``hedge_delay_s()`` call yields seconds). Anything opaque —
    literals, subscripts, nested arithmetic — is unit-less and never
    flagged; the rule only fires when BOTH sides are known."""
    if isinstance(node, ast.Name):
        return unit_of_name(node.id)
    if isinstance(node, ast.Attribute):
        return unit_of_name(node.attr)
    if isinstance(node, ast.Call):
        return unit_of_expr(node.func)
    if isinstance(node, ast.UnaryOp):
        return unit_of_expr(node.operand)
    return None


# ---------------------------------------------------------------- lane

# The canonical same-instant total order. fleet/events.py is the one
# place these are defined; lane_order_problems() holds the two in
# bijection so neither can drift.
CANONICAL_LANES: Tuple[Tuple[str, int], ...] = (
    ("LANE_ARRIVAL", 0),
    ("LANE_COMPLETION", 1),
    ("LANE_CHAOS", 2),
    ("LANE_HEALTH_PROBE", 3),
    ("LANE_AUTOSCALER", 4),
    ("LANE_PLANNER", 5),
    ("LANE_KV_TRANSFER", 6),
    ("LANE_MODEL_SWAP", 7),
    ("LANE_INTEGRITY_AUDIT", 8),
)
LANE_NAMES = frozenset(name for name, _ in CANONICAL_LANES)

_LANE_HOME = "fleet/events.py"


def _terminal_name(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


# --------------------------------------------------------------- drift


def _is_dataclass(node: ast.ClassDef) -> bool:
    for dec in node.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        name = _terminal_name(target)
        if name == "dataclass":
            return True
    return False


def _dataclass_fields(node: ast.ClassDef) -> List[Tuple[str, int, int]]:
    """(name, line, col) of every dataclass field: annotated
    assignments in the class body, minus ClassVar declarations."""
    fields: List[Tuple[str, int, int]] = []
    for stmt in node.body:
        if not isinstance(stmt, ast.AnnAssign):
            continue
        if not isinstance(stmt.target, ast.Name):
            continue
        ann = ast.dump(stmt.annotation)
        if "ClassVar" in ann:
            continue
        fields.append(
            (stmt.target.id, stmt.lineno, stmt.col_offset))
    return fields


def _as_dict_coverage(fn: ast.FunctionDef) -> Tuple[bool, set]:
    """What ``as_dict`` serializes: every string literal (report
    keys) and every ``self.<attr>`` access. ``asdict(self)`` /
    ``dataclasses.asdict(self)`` covers everything at once."""
    covered: set = set()
    for sub in ast.walk(fn):
        if (isinstance(sub, ast.Call)
                and _terminal_name(sub.func) == "asdict"
                and sub.args
                and isinstance(sub.args[0], ast.Name)
                and sub.args[0].id == "self"):
            # asdict(self) serializes every field; asdict(self.slo)
            # serializes a SUB-config and covers nothing here
            return True, covered
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            covered.add(sub.value)
        if (isinstance(sub, ast.Attribute)
                and isinstance(sub.value, ast.Name)
                and sub.value.id == "self"):
            covered.add(sub.attr)
    return False, covered


# ------------------------------------------------------------- visitor


class _Visitor(ast.NodeVisitor):
    def __init__(self, path: str, norm: str):
        self.path = path
        self.norm = norm  # forward-slash path for location checks
        self.findings: List[Finding] = []

    def _emit(self, node: ast.AST, rule: str, message: str) -> None:
        self.findings.append(Finding(
            self.path, getattr(node, "lineno", 0),
            getattr(node, "col_offset", 0), rule, message))

    # -- unit ---------------------------------------------------------

    def _check_pair(self, node: ast.AST, left: ast.AST,
                    right: ast.AST, what: str) -> None:
        lu, ru = unit_of_expr(left), unit_of_expr(right)
        if lu and ru and lu != ru:
            self._emit(node, "unit",
                       f"{what} mixes units {lu} and {ru} — "
                       "convert explicitly (multiply/divide) first")

    def visit_BinOp(self, node):            # noqa: N802
        if isinstance(node.op, (ast.Add, ast.Sub)):
            self._check_pair(node, node.left, node.right,
                             "arithmetic")
        self.generic_visit(node)

    def visit_AugAssign(self, node):        # noqa: N802
        if isinstance(node.op, (ast.Add, ast.Sub)):
            self._check_pair(node, node.target, node.value,
                             "augmented assignment")
        self.generic_visit(node)

    def visit_Compare(self, node):          # noqa: N802
        left = node.left
        for comparator in node.comparators:
            self._check_pair(node, left, comparator, "comparison")
            left = comparator
        self.generic_visit(node)

    def visit_Call(self, node):             # noqa: N802
        for kw in node.keywords:
            if kw.arg is None:
                continue
            pu = unit_of_name(kw.arg)
            vu = unit_of_expr(kw.value)
            if pu and vu and pu != vu:
                self._emit(
                    kw.value, "unit",
                    f"keyword {kw.arg!r} (unit {pu}) receives a "
                    f"{vu} value — convert explicitly first")
        self._check_push(node)
        self.generic_visit(node)

    # -- lane ---------------------------------------------------------

    def _check_push(self, node: ast.Call) -> None:
        """Every EventHeap.push lane argument must be a registered
        LANE_* constant — computed lanes break the same-instant
        total order. Matched structurally: a method named ``push``
        called with (time, lane, payload)."""
        if not (isinstance(node.func, ast.Attribute)
                and node.func.attr == "push"
                and len(node.args) == 3):
            return
        lane = node.args[1]
        name = _terminal_name(lane)
        if name is None:
            self._emit(
                lane, "lane",
                "EventHeap.push lane must be a registered LANE_* "
                "constant, not a computed expression")
        elif name not in LANE_NAMES:
            self._emit(
                lane, "lane",
                f"EventHeap.push lane {name!r} is not a registered "
                "lane constant (fleet/events.py LANES)")

    def visit_Assign(self, node):           # noqa: N802
        # a LANE_* name bound to an integer is a lane (re)definition;
        # only fleet/events.py may do that. (Non-integer LANE_*
        # bindings — sets, tuples of lanes — are bookkeeping, not
        # redefinitions.)
        if (not self.norm.endswith(_LANE_HOME)
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, int)):
            for target in node.targets:
                name = _terminal_name(target)
                if name and name.startswith("LANE_"):
                    self._emit(
                        node, "lane",
                        f"{name} defined outside {_LANE_HOME} — "
                        "lane constants have exactly one home")
        self.generic_visit(node)

    # -- drift --------------------------------------------------------

    def visit_ClassDef(self, node):         # noqa: N802
        if node.name.endswith("Config") and _is_dataclass(node):
            self._check_config(node)
        self.generic_visit(node)

    def _check_config(self, node: ast.ClassDef) -> None:
        as_dict = next(
            (s for s in node.body
             if isinstance(s, ast.FunctionDef)
             and s.name == "as_dict"), None)
        if as_dict is None:
            return
        full, covered = _as_dict_coverage(as_dict)
        if full:
            return
        for fname, line, col in _dataclass_fields(node):
            if fname not in covered:
                self.findings.append(Finding(
                    self.path, line, col, "drift",
                    f"{node.name}.{fname} is not serialized by "
                    "as_dict — report drift (the "
                    "hedge_budget_burst class); serialize it or "
                    "waive with the reason"))


# ------------------------------------------------------------ lint API


def lint_source(source: str, path: str = "<string>"
                ) -> List[Finding]:
    norm = path.replace("\\", "/")
    try:
        tree = ast.parse(source)
    except SyntaxError as exc:
        return [Finding(path, exc.lineno or 0, 0, "drift",
                        f"syntax error: {exc.msg}")]
    visitor = _Visitor(path, norm)
    visitor.visit(tree)
    return lintcore.apply_waivers(
        visitor.findings, source, path, "contractlint", RULES)


def iter_py_files(paths: Sequence[str]) -> List[str]:
    return lintcore.iter_py_files(paths)


def lint_paths(paths: Sequence[str]) -> List[Finding]:
    return lintcore.lint_paths(paths, lint_source)


def report(findings: Iterable[Finding],
           files: Optional[int] = None) -> dict:
    return lintcore.report(findings, RULES, files)


# -------------------------------------------------- registry bijections


def lane_order_problems() -> List[str]:
    """fleet/events.py lane table <-> the canonical same-instant
    order, held in bijection: same names, same values, LANES sorted
    and gap-free. Both fleet and globe import from that one table,
    so this single check covers every push site's ordering."""
    from kind_tpu_sim.fleet import events

    problems: List[str] = []
    for name, value in CANONICAL_LANES:
        have = getattr(events, name, None)
        if have is None:
            problems.append(
                f"fleet/events.py is missing lane constant {name}")
        elif have != value:
            problems.append(
                f"{name} is {have}, canonical order says {value} "
                "(arrival < completion < chaos < probe < "
                "autoscaler < planner < kv-transfer < model-swap)")
    lanes = getattr(events, "LANES", ())
    want = tuple(v for _, v in CANONICAL_LANES)
    if tuple(lanes) != want:
        problems.append(
            f"events.LANES is {tuple(lanes)!r}, expected the "
            f"canonical {want!r}")
    for extra in dir(events):
        if extra.startswith("LANE_") and extra not in LANE_NAMES:
            problems.append(
                f"fleet/events.py defines {extra}, which the "
                "canonical lane table does not know — register it "
                "in contractlint.CANONICAL_LANES")
    return problems


RootLike = Optional[Union[pathlib.Path, str]]


def _resolve_root(root: RootLike) -> pathlib.Path:
    """Repo root for the cross-checks; accepts a str for library
    callers, defaults to the checkout containing this file."""
    if root is None:
        return pathlib.Path(__file__).resolve().parents[2]
    return pathlib.Path(root)


def knob_coverage_problems(root: RootLike = None) -> List[str]:
    """Knob registry <-> code, both directions. detlint's
    `unknown-knob` rule already rejects unregistered KIND_TPU_SIM_*
    tokens; this is the reverse: a registered knob whose alias
    constant no module ever reads is dead weight (or a rename that
    left the registry behind)."""
    from kind_tpu_sim.analysis import knobs

    root = _resolve_root(root)
    aliases: Dict[str, str] = {}
    for attr in dir(knobs):
        if attr.startswith("_"):
            continue
        value = getattr(knobs, attr)
        if isinstance(value, str) and knobs.is_registered(value):
            aliases[value] = attr

    searched: List[str] = []
    pkg = root / "kind_tpu_sim"
    if pkg.is_dir():
        searched.extend(
            str(f) for f in sorted(pkg.rglob("*.py"))
            if "__pycache__" not in f.parts
            and f.name != "knobs.py")
    searched.extend(
        str(f) for f in sorted(root.glob("*.py")))
    corpus = []
    for fname in searched:
        try:
            with open(fname, encoding="utf-8") as fh:
                corpus.append(fh.read())
        except OSError:
            continue
    text = "\n".join(corpus)

    problems: List[str] = []
    for name in sorted(knobs.REGISTRY):
        alias = aliases.get(name)
        read = name in text or (
            alias is not None
            and re.search(r"\b" + re.escape(alias) + r"\b", text))
        if not read:
            problems.append(
                f"knob {name} is registered but never read outside "
                "the registry — dead knob or rename drift")
    return problems


def cli_flag_problems(root: RootLike = None) -> List[str]:
    """CLI flags <-> config fields, for the unit-carrying subset: a
    ``--foo-bar-s`` flag must correspond to a real ``foo_bar_s``
    dataclass field (or function parameter) somewhere in the
    package. Catches the rename-the-field-forget-the-flag drift for
    every flag that encodes a unit in its name."""
    root = _resolve_root(root)
    cli_path = root / "kind_tpu_sim" / "cli.py"
    try:
        cli_tree = ast.parse(cli_path.read_text(encoding="utf-8"))
    except (OSError, SyntaxError) as exc:
        return [f"cannot parse {cli_path}: {exc}"]

    flags: List[Tuple[str, int]] = []
    for node in ast.walk(cli_tree):
        if not (isinstance(node, ast.Call)
                and _terminal_name(node.func) == "add_argument"):
            continue
        for arg in node.args:
            if (isinstance(arg, ast.Constant)
                    and isinstance(arg.value, str)
                    and arg.value.startswith("--")):
                name = arg.value[2:].replace("-", "_")
                if unit_of_name(name):
                    flags.append((name, node.lineno))

    # every dataclass field + function parameter name in the package
    names: set = set()
    pkg = root / "kind_tpu_sim"
    for fname in sorted(pkg.rglob("*.py")):
        if "__pycache__" in fname.parts:
            continue
        try:
            tree = ast.parse(fname.read_text(encoding="utf-8"))
        except (OSError, SyntaxError):
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.AnnAssign) and isinstance(
                    node.target, ast.Name):
                names.add(node.target.id)
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                for a in (node.args.args + node.args.kwonlyargs):
                    names.add(a.arg)

    problems: List[str] = []
    for flag, line in sorted(set(flags)):
        if flag not in names:
            problems.append(
                f"cli.py:{line}: flag --{flag.replace('_', '-')} "
                "names no config field or parameter "
                f"{flag!r} — flag/field drift")
    return problems


def generation_coverage_problems(root: RootLike = None) -> List[str]:
    """Generation registry <-> configs/manifests, every direction
    (docs/ZOO.md): each registered generation must resolve to a
    loadable ``fleet/calibration/<gen>.json``; the accelerator-label
    maps (costmodel.ACCELERATOR_GENERATIONS and its inverse, the
    sched-topology table) must stay in bijection with
    ``topology.ACCELERATORS``; and every accelerator nodeSelector in
    ``pods/*.yaml`` must name a label that prices against a
    registered generation. Catches the add-a-generation-forget-the-
    calibration (and label-rename) drift before a sim prices against
    a file that is not there."""
    import yaml

    from kind_tpu_sim import topology
    from kind_tpu_sim.fleet import costmodel

    root = _resolve_root(root)
    problems: List[str] = []

    for gen in costmodel.GENERATIONS:
        path = (root / "kind_tpu_sim" / "fleet" / "calibration"
                / f"{gen}.json")
        if not path.is_file():
            problems.append(
                f"generation {gen!r} is registered but "
                f"{path.relative_to(root)} does not exist — run "
                "`kind-tpu-sim fleet calibrate`")
            continue
        try:
            costmodel.load_generation(gen)
        except Exception as exc:
            problems.append(
                f"generation {gen!r} calibration does not load: "
                f"{exc}")
    for gen in sorted(costmodel.GENERATION_FACTS):
        if gen not in costmodel.GENERATIONS:
            problems.append(
                f"GENERATION_FACTS names unregistered generation "
                f"{gen!r}")

    for accel in sorted(topology.ACCELERATORS):
        if accel not in costmodel.ACCELERATOR_GENERATIONS:
            problems.append(
                f"accelerator {accel!r} has no generation mapping "
                "(costmodel.ACCELERATOR_GENERATIONS) — sched fleets "
                "of it cannot be priced")
        if accel not in costmodel.GENERATION_SCHED_TOPOLOGY:
            problems.append(
                f"accelerator {accel!r} has no sched-topology entry "
                "(costmodel.GENERATION_SCHED_TOPOLOGY)")
    for accel, gen in sorted(costmodel.ACCELERATOR_GENERATIONS
                             .items()):
        if accel not in topology.ACCELERATORS:
            problems.append(
                f"ACCELERATOR_GENERATIONS names unknown accelerator "
                f"{accel!r} (topology.ACCELERATORS)")
        if gen not in costmodel.GENERATIONS:
            problems.append(
                f"accelerator {accel!r} maps to unregistered "
                f"generation {gen!r}")

    def _labels(obj) -> List[str]:
        found: List[str] = []
        if isinstance(obj, dict):
            for key, value in obj.items():
                if (key == topology.LABEL_ACCELERATOR
                        and isinstance(value, str)):
                    found.append(value)
                else:
                    found.extend(_labels(value))
        elif isinstance(obj, list):
            for value in obj:
                found.extend(_labels(value))
        return found

    pods = root / "pods"
    for manifest in sorted(pods.glob("*.yaml")):
        try:
            docs = list(yaml.safe_load_all(
                manifest.read_text(encoding="utf-8")))
        except Exception as exc:
            problems.append(
                f"{manifest.relative_to(root)}: unparseable yaml: "
                f"{exc}")
            continue
        for label in _labels(docs):
            try:
                costmodel.generation_of_accelerator(label)
            except ValueError:
                problems.append(
                    f"{manifest.relative_to(root)}: accelerator "
                    f"label {label!r} resolves to no registered "
                    "generation")
    return problems


def cross_check_problems(root: RootLike = None) -> Dict[str, List[str]]:
    """All registry bijections the contract gate holds, by family.
    fault-schemas and scenario-registry checks are shared with
    `analysis lint` (they were born there); lanes, knob coverage,
    and CLI flags are contractlint's own."""
    from kind_tpu_sim.chaos import fault_schema_problems
    from kind_tpu_sim.scenarios import registry

    return {
        "cli_flags": cli_flag_problems(root),
        "fault_schemas": fault_schema_problems(),
        "generation_coverage": generation_coverage_problems(root),
        "knob_coverage": knob_coverage_problems(root),
        "lane_order": lane_order_problems(),
        "scenario_registry": registry.registry_problems(),
    }


# ------------------------------------------------------- report schema

SCHEMA_PATH = pathlib.Path(__file__).with_name("report_schema.json")

# Containers whose keys are run-dependent (replica ids, zone names,
# gang names, event types, counter names): the child segment is
# collapsed to `*` so the schema pins structure, not instance names.
_DYNAMIC_CONTAINERS = frozenset((
    "breakers", "candidates", "cells", "components",
    "event_counts", "finalists", "fleet_counters", "gangs",
    "generations", "globe_counters", "hard_limits",
    "health_counters", "mix", "peak_outstanding", "per_model_slo",
    "per_replica", "replicas", "residents", "retry_budget",
    "sched_counters", "sched_event_counts", "tenants",
    "hedge_budget_by_tenant", "train_counters", "warm", "zones",
))


def _key_paths(obj: object, prefix: Tuple[str, ...] = ()
               ) -> set:
    out: set = set()
    if isinstance(obj, dict):
        parent = prefix[-1] if prefix else ""
        for key, value in obj.items():
            seg = "*" if parent in _DYNAMIC_CONTAINERS else str(key)
            out |= _key_paths(value, prefix + (seg,))
    elif isinstance(obj, (list, tuple)):
        if not obj:
            out.add(".".join(prefix) + "[]")
        for value in obj:
            out |= _key_paths(value, prefix + ("[]",))
    else:
        out.add(".".join(prefix))
    return out


def board_counter_keys(root: RootLike = None) -> Dict[str, List[str]]:
    """Statically-extracted counter/gauge names per metrics board:
    every ``metrics.<x>_board().incr("name")`` / ``.gauge("name")``
    literal in the package. Dynamic names (f-strings) are recorded
    as ``*``. This is the full *possible* key-space — run-independent
    by construction, unlike observing one simulation."""
    root = _resolve_root(root)
    boards: Dict[str, set] = {}
    pkg = root / "kind_tpu_sim"
    for fname in sorted(pkg.rglob("*.py")):
        if "__pycache__" in fname.parts:
            continue
        try:
            tree = ast.parse(fname.read_text(encoding="utf-8"))
        except (OSError, SyntaxError):
            continue
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("incr", "gauge")
                    and node.args):
                continue
            recv = node.func.value
            board = (_terminal_name(recv.func)
                     if isinstance(recv, ast.Call) else None)
            if board is None or not board.endswith("_board"):
                continue
            key = node.args[0]
            name = (key.value
                    if isinstance(key, ast.Constant)
                    and isinstance(key.value, str) else "*")
            boards.setdefault(board, set()).add(name)
    return {b: sorted(ks) for b, ks in sorted(boards.items())}


def collect_report_schema(
        root: RootLike = None) -> dict:
    """The current report key-space: seeded tiny fleet and globe
    runs (every optional subsystem enabled so conditional keys
    appear), plus the statically-extracted board counters. Seeds and
    workloads are pinned — the schema is a pure function of the
    code, so CI can diff it."""
    from kind_tpu_sim import fleet, globe

    fspec = fleet.WorkloadSpec(
        process="poisson", rps=40.0, n_requests=40)
    fcfg = fleet.FleetConfig(
        replicas=2, policy="least-outstanding", autoscale=True,
        sched=fleet.FleetSchedConfig(),
        health=fleet.DetectorConfig.from_env(),
        overload=fleet.OverloadConfig(),
        training=fleet.TrainingConfig(gangs=(
            fleet.TrainingGangConfig(
                name="llm0", topology="2x8", total_steps=10),)))
    fleet_report = fleet.FleetSim(
        fcfg, fleet.generate_trace(fspec, 3)).run()

    gspec = globe.GlobeWorkloadSpec(n_per_zone=20, rps=20.0)
    gcfg = globe.GlobeConfig(
        zones=("us-a", "eu-b"), max_virtual_s=60.0, workload=gspec,
        autoscale=True, overload=fleet.OverloadConfig(hedge=True))
    globe_report = globe.GlobeSim(
        gcfg, globe.generate_globe_traces(gcfg, 5)).run()

    # disagg keys (pools / kv / calibration / itl / router kv lane)
    # only exist on a phase-split fleet, which excludes sched — so
    # they get their own pinned run instead of riding the main one
    dspec = fleet.WorkloadSpec(
        process="poisson", rps=40.0, n_requests=40)
    dcfg = fleet.FleetConfig(
        replicas=4, policy="least-outstanding", autoscale=True,
        overload=fleet.OverloadConfig(),
        disagg=fleet.DisaggConfig(prefill_replicas=2,
                                  decode_replicas=2))
    disagg_report = fleet.FleetSim(
        dcfg, fleet.generate_trace(dspec, 7)).run()

    # tenancy keys (per-tenant books / fair_queue / per-tenant
    # overload budgets) only exist on a tenanted fleet — its own
    # pinned run too. Tenant names come from the pinned
    # default_tenancy population, so tenancy.slo's per-tier keys
    # stay a pure function of the code.
    tten = fleet.default_tenancy()
    tspec = fleet.WorkloadSpec(
        process="poisson", rps=40.0, n_requests=40, tenancy=tten)
    tcfg = fleet.FleetConfig(
        replicas=2, policy="least-outstanding",
        overload=fleet.OverloadConfig(), tenancy=tten)
    tenant_report = fleet.FleetSim(
        tcfg, fleet.generate_trace(tspec, 9)).run()

    # zoo keys (per-model SLO boards / residents / swap ledger /
    # per-replica generation labels; globe warm-model maps) only
    # exist on a zoo fleet — pinned runs of their own
    # (docs/ZOO.md). Model- and replica-keyed containers are
    # dynamic, so their child segments collapse to `*`.
    zzoo = fleet.default_zoo()
    zspec = fleet.WorkloadSpec(
        process="poisson", rps=40.0, n_requests=40, zoo=zzoo)
    zcfg = fleet.FleetConfig(
        replicas=2, policy="least-outstanding", zoo=zzoo,
        generations=("v5e", "v5p"))
    zoo_report = fleet.FleetSim(
        zcfg, fleet.generate_trace(zspec, 11)).run()

    gzcfg = globe.GlobeConfig(
        zones=("us-a", "eu-b"), max_virtual_s=60.0,
        workload=globe.GlobeWorkloadSpec(n_per_zone=20, rps=20.0),
        sched=False, zoo=zzoo, generations=("v5e", "v5p"))
    globe_zoo_report = globe.GlobeSim(
        gzcfg, globe.generate_globe_traces(gzcfg, 13)).run()

    # tune keys (search trace / pareto front / chaos rescoring): a
    # pinned tiny search over the disagg-ratio space. The
    # candidate-index keyed containers ("candidates", chaos
    # "finalists") are dynamic — their child segments collapse to `*`
    from kind_tpu_sim import tune

    tune_report = tune.tune(
        tune.ratio_space(("1:3", "2:2", "3:1")),
        fleet.WorkloadSpec(process="poisson", rps=50.0,
                           n_requests=40, prompt_len=(8, 16),
                           max_new=(4, 8)),
        fleet.SloPolicy(ttft_s=0.5, e2e_s=2.0),
        seed=0, budget=4, chaos_budget=1)

    return {
        "boards": board_counter_keys(root),
        "fleet": sorted(_key_paths(fleet_report)),
        "fleet_disagg": sorted(_key_paths(disagg_report)),
        "fleet_tenant": sorted(_key_paths(tenant_report)),
        "fleet_zoo": sorted(_key_paths(zoo_report)),
        "globe": sorted(_key_paths(globe_report)),
        "globe_zoo": sorted(_key_paths(globe_zoo_report)),
        "tune": sorted(_key_paths(tune_report)),
    }


def schema_problems(have: dict, want: dict) -> List[str]:
    """Diff the checked-in schema against the collected one. Every
    added or removed key path is a problem line — report drift must
    arrive as an explicit regenerate-and-review, never silently."""
    problems: List[str] = []
    for section in sorted(set(have) | set(want)):
        h = have.get(section)
        w = want.get(section)
        if isinstance(h, dict) or isinstance(w, dict):
            h = h or {}
            w = w or {}
            for board in sorted(set(h) | set(w)):
                hs, ws = set(h.get(board, ())), set(w.get(board, ()))
                for key in sorted(ws - hs):
                    problems.append(
                        f"{section}.{board}: new key {key!r} not in "
                        "checked-in schema")
                for key in sorted(hs - ws):
                    problems.append(
                        f"{section}.{board}: key {key!r} vanished "
                        "from the code")
        else:
            hs, ws = set(h or ()), set(w or ())
            for key in sorted(ws - hs):
                problems.append(
                    f"{section}: new report key {key!r} not in "
                    "checked-in schema")
            for key in sorted(hs - ws):
                problems.append(
                    f"{section}: report key {key!r} vanished from "
                    "the report")
    if problems:
        problems.append(
            "regenerate with `kind-tpu-sim analysis contract "
            "--write-schema` and review the diff")
    return problems


def load_schema(path: Optional[pathlib.Path] = None) -> dict:
    path = path or SCHEMA_PATH
    try:
        with open(path, encoding="utf-8") as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return {}


def write_schema(path: Optional[pathlib.Path] = None,
                 root: RootLike = None) -> dict:
    path = path or SCHEMA_PATH
    schema = collect_report_schema(root)
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(schema, fh, indent=1, sort_keys=True)
        fh.write("\n")
    return schema
