"""replaycheck — the runtime replay-divergence bisector.

detlint catches the hazard *classes* statically; this module checks
the contract itself at runtime: run a scenario twice under the same
seed, flatten each report into its canonical event stream, hash the
stream incrementally, and — on mismatch — **bisect the prefix-digest
arrays to the first divergent event**, printing both sides' context.
A failing byte-identity assert used to say "reports differ"; the
bisector says *"event 143 (stream completions): run 0 finished
request zone-b/r17 at 3.41s, run 1 at 3.42s"* — the difference
between an afternoon of print-debugging and a one-line diff.

Event extraction is structural: every list under a known stream key
(``completions``, ``events``, ``chaos``, ``runs``) anywhere in the
report becomes a sequence of indexed events (path-labeled, traversed
in sorted-key order), and everything else collapses into one final
``report`` summary event — so a divergence anywhere in the document
is localized to the tightest unit the report offers.

Targets (:data:`REPLAY_TARGETS`) cover every virtual-clock layer:
direct ``fleet-run`` / ``sched-run`` / ``globe-run`` sims plus the
deterministic chaos scenarios (``globe-zone-loss`` etc.). The sim
targets also support a **deliberately injected entropy bug**
(``inject=True`` perturbs the second run's workload mid-stream) — the
self-test proving the bisector finds and names the first divergent
event rather than just declaring failure.

CLI: ``kind-tpu-sim analysis replay --scenario globe-zone-loss``.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Callable, Dict, List, Optional, Sequence, Tuple

STREAM_KEYS = ("completions", "events", "chaos", "runs")

# How many events of leading context ride along with a divergence.
CONTEXT_EVENTS = 2


# -- event stream extraction ------------------------------------------


def event_stream(report: dict) -> List[dict]:
    """Flatten a report into its canonical event sequence: one entry
    per element of every stream-keyed list (any depth, sorted-key
    traversal), then a final summary event with the streams elided."""
    events: List[dict] = []

    def walk(obj, path):
        if isinstance(obj, dict):
            rest = {}
            for key in sorted(obj):
                value = obj[key]
                label = f"{path}{key}"
                if key in STREAM_KEYS and isinstance(value, list):
                    for i, item in enumerate(value):
                        events.append({"stream": label, "index": i,
                                       "event": item})
                    rest[key] = f"<stream: {len(value)} events>"
                elif isinstance(value, (dict, list)):
                    rest[key] = walk(value, label + ".")
                else:
                    rest[key] = value
            return rest
        if isinstance(obj, list):
            return [walk(item, path) for item in obj]
        return obj

    summary = walk(report, "")
    events.append({"stream": "report", "index": 0, "event": summary})
    return events


def event_digest(event: dict) -> str:
    """Canonical per-event digest (sorted-keys JSON, sha256)."""
    blob = json.dumps(event, sort_keys=True, separators=(",", ":"),
                      default=str)
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()


def prefix_digests(events: Sequence[dict]) -> List[str]:
    """Rolling digests: entry i commits to events[0..i]. Two streams
    are byte-identical iff their final entries match — and the first
    index where the arrays differ IS the first divergent event."""
    out: List[str] = []
    h = ""
    for ev in events:
        h = hashlib.sha256(
            (h + event_digest(ev)).encode("ascii")).hexdigest()
        out.append(h)
    return out


@dataclasses.dataclass(frozen=True)
class Divergence:
    index: int
    stream: str
    a: Optional[dict]       # the event on run 0 (None: stream ended)
    b: Optional[dict]       # ... and on the diverging run
    context: List[dict]     # shared events just before the split

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


def first_divergence(a: Sequence[dict], b: Sequence[dict]
                     ) -> Optional[Divergence]:
    """Binary-search the prefix-digest arrays for the first index
    where the two event streams disagree (None: identical)."""
    pa, pb = prefix_digests(a), prefix_digests(b)
    n = min(len(pa), len(pb))
    # the rolling digest commits to the whole prefix: equal finals +
    # equal lengths => identical streams, no scan needed
    if len(pa) == len(pb) and (not pa or pa[-1] == pb[-1]):
        return None
    lo, hi = 0, n  # smallest i in [0, n] with pa[i] != pb[i]
    while lo < hi:
        mid = (lo + hi) // 2
        if pa[mid] == pb[mid]:
            lo = mid + 1
        else:
            hi = mid
    idx = lo  # == n means one stream is a strict prefix of the other
    ev_a = a[idx] if idx < len(a) else None
    ev_b = b[idx] if idx < len(b) else None
    stream = (ev_a or ev_b or {}).get("stream", "report")
    context = list(a[max(0, idx - CONTEXT_EVENTS):idx])
    return Divergence(index=idx, stream=stream, a=ev_a, b=ev_b,
                      context=context)


# -- replay targets ---------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ReplayTarget:
    """``runner(seed, inject)`` returns one report dict. ``inject``
    perturbs the run's *workload* mid-stream (the modeled stray-
    entropy bug); targets that cannot inject raise ValueError."""

    name: str
    description: str
    runner: Callable[[int, bool], dict]
    slow: bool = False
    injectable: bool = False


def _inject_trace(trace: list):
    """The modeled entropy bug: one request near the middle of the
    stream grows its decode length by one token — exactly the shape
    of an unseeded sample leaking into a replayed run."""
    import dataclasses as dc

    mid = len(trace) // 2
    trace[mid] = dc.replace(trace[mid],
                            max_new=trace[mid].max_new + 1)


def _run_fleet(seed: int, inject: bool) -> dict:
    from kind_tpu_sim import fleet

    spec = fleet.WorkloadSpec(process="poisson", rps=60.0,
                              n_requests=120)
    trace = fleet.generate_trace(spec, seed)
    if inject:
        _inject_trace(trace)
    cfg = fleet.FleetConfig(replicas=2, policy="least-outstanding")
    return fleet.FleetSim(cfg, trace).run()


def _run_sched(seed: int, inject: bool) -> dict:
    from kind_tpu_sim import sched

    if inject:
        raise ValueError("sched-run does not support injection")
    cfg = sched.SchedSimConfig(
        workload=sched.SchedWorkloadSpec(n_gangs=16))
    return sched.run_sched_sim(cfg, seed)


def _run_globe(seed: int, inject: bool) -> dict:
    from kind_tpu_sim import globe

    cfg = globe.GlobeConfig(
        zones=("zone-a", "zone-b"), replicas_per_cell=2,
        workload=globe.GlobeWorkloadSpec(process="poisson",
                                         rps=30.0, n_per_zone=60))
    traces = globe.generate_globe_traces(cfg, seed)
    if inject:
        _inject_trace(traces[sorted(traces)[0]])
    return globe.GlobeSim(cfg, traces=traces, seed=seed).run()


# alternates the drivers call by call (run 0 single-process, run 1
# sharded, ...) so replay()'s byte-identity verdict IS the
# cross-driver referee — any mixed sequence must agree anyway
_GLOBE_SHARD_FLIP = [1]


def _run_globe_sharded(seed: int, inject: bool) -> dict:
    from kind_tpu_sim import globe

    cfg = globe.GlobeConfig(
        zones=("zone-a", "zone-b"), cells_per_zone=2,
        replicas_per_cell=2,
        workload=globe.GlobeWorkloadSpec(process="poisson",
                                         rps=30.0, n_per_zone=60))
    traces = globe.generate_globe_traces(cfg, seed)
    if inject:
        _inject_trace(traces[sorted(traces)[0]])
    _GLOBE_SHARD_FLIP[0] ^= 1
    if _GLOBE_SHARD_FLIP[0]:
        return globe.ShardedGlobeSim(cfg, traces=traces,
                                     seed=seed, shards=2).run()
    return globe.GlobeSim(cfg, traces=traces, seed=seed).run()


def _run_tune(seed: int, inject: bool) -> dict:
    """A small in-process tune search (docs/TUNE.md): the whole
    search trace rides in the report's ``runs`` stream, so the
    bisector localizes a divergence to one candidate evaluation."""
    if inject:
        raise ValueError("tune does not support injection; the "
                         "search consumes generated traces — use "
                         "fleet-run")
    from kind_tpu_sim import fleet, tune

    space = tune.ratio_space(("1:3", "2:2", "3:1"))
    workload = fleet.WorkloadSpec(process="poisson", rps=50.0,
                                  n_requests=40,
                                  prompt_len=(8, 16),
                                  max_new=(4, 8))
    slo = fleet.SloPolicy(ttft_s=0.5, e2e_s=2.0)
    return tune.tune(space, workload, slo, seed=seed, budget=4,
                     chaos_budget=1)


def _scenario_runner(name: str):
    def run(seed: int, inject: bool) -> dict:
        if inject:
            raise ValueError(
                f"scenario target {name!r} does not support "
                "injection; use fleet-run or globe-run")
        from kind_tpu_sim import chaos

        return chaos.run_scenario(name, seed=seed)
    return run


# driver-level targets: direct sim runs and cross-driver referees,
# not chaos scenarios. Everything in REPLAY_TARGETS outside this
# tuple MUST come from the scenario registry's replayable set — the
# bijection test in tests/test_scenarios.py pins that, so a new
# driver target belongs here, not in an ad-hoc test exclusion.
DRIVER_TARGETS = ("fleet-run", "sched-run", "globe-run",
                  "globe-sharded", "tune")


def _targets() -> Dict[str, ReplayTarget]:
    # The scenario targets derive from the registry's `replayable`
    # flags (scenarios/registry.py) — the single declaration of
    # which reports are pure functions of (config, seed). The
    # worker-process scenarios (pids, wall timings) and the
    # jax-engine ones (slow) are declared non-replayable there, so a
    # new scenario can never be silently missing from this list.
    from kind_tpu_sim.scenarios import registry

    out = {
        "fleet-run": ReplayTarget(
            "fleet-run", "direct FleetSim run (120 poisson "
            "requests, 2 replicas)", _run_fleet, injectable=True),
        "sched-run": ReplayTarget(
            "sched-run", "direct scheduler sim run (16 gangs)",
            _run_sched),
        "globe-run": ReplayTarget(
            "globe-run", "direct GlobeSim run (2 zones)",
            _run_globe, injectable=True),
        "globe-sharded": ReplayTarget(
            "globe-sharded", "GlobeSim vs ShardedGlobeSim(2) on "
            "one seed — the cross-driver byte-identity referee",
            _run_globe_sharded, slow=True, injectable=True),
        "tune": ReplayTarget(
            "tune", "in-process tune search over the disagg-ratio "
            "space (budget 4, chaos 1), full search trace",
            _run_tune),
    }
    for name in registry.replayable_names():
        out[name] = ReplayTarget(
            name, f"chaos scenario {name!r}, full report",
            _scenario_runner(name))
    return out


REPLAY_TARGETS: Dict[str, ReplayTarget] = _targets()


# -- the check --------------------------------------------------------


def replay(target: str, seed: Optional[int] = None, runs: int = 2,
           inject: bool = False) -> dict:
    """Run ``target`` ``runs`` times under one seed; byte-identity of
    the event streams is the verdict. ``inject=True`` plants the
    entropy bug in every run after the first — the report must then
    name the first divergent event (bisector self-test)."""
    if target not in REPLAY_TARGETS:
        known = ", ".join(sorted(REPLAY_TARGETS))
        raise ValueError(f"unknown replay target {target!r}; "
                         f"known: {known}")
    if runs < 2:
        raise ValueError("replay needs runs >= 2")
    from kind_tpu_sim.chaos import resolve_seed

    seed = resolve_seed(seed)
    t = REPLAY_TARGETS[target]
    streams: List[Tuple[List[dict], List[str]]] = []
    for i in range(runs):
        report = t.runner(seed, inject and i > 0)
        events = event_stream(report)
        streams.append((events, prefix_digests(events)))
    base_events, base_prefix = streams[0]
    divergence = None
    diverged_run = None
    for i in range(1, runs):
        events_i, prefix_i = streams[i]
        if (len(prefix_i) == len(base_prefix)
                and (not prefix_i or prefix_i[-1] == base_prefix[-1])):
            continue
        divergence = first_divergence(base_events, events_i)
        diverged_run = i
        break
    out = {
        "target": target,
        "seed": seed,
        "runs": runs,
        "injected": bool(inject),
        "events": len(base_events),
        "stream_digest": (base_prefix[-1] if base_prefix else ""),
        "ok": divergence is None,
    }
    if divergence is not None:
        out["diverged_run"] = diverged_run
        out["divergence"] = divergence.as_dict()
    return out


def list_targets() -> List[dict]:
    return [
        {"name": t.name, "description": t.description,
         "slow": t.slow, "injectable": t.injectable}
        for _, t in sorted(REPLAY_TARGETS.items())
    ]
