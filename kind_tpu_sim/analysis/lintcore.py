"""The shared sanitizer core — findings, waivers, file walking.

Two static sanitizers guard the simulator's contracts: **detlint**
(determinism hazards: wall-clock reads, unseeded entropy, unordered
iteration, unsorted JSON — docs/ANALYSIS.md) and **contractlint**
(interface contracts: unit-suffix mixing, ``as_dict`` drift,
event-lane ordering). Both speak the same waiver grammar::

    expr  # <tool>: ok(rule[, rule...]) -- <why this is safe>

(with ``<tool>`` being ``detlint`` or ``contractlint``; the comment
may also sit alone on the line directly above). The grammar's three
hard rules live HERE, once, so the tools cannot drift apart:

* a waiver without a reason is itself a finding — *fix or justify*,
  never silence;
* a waiver naming a rule the tool doesn't have is a finding — a
  typo'd rule name must not silently waive nothing;
* a waiver matching no finding on its line is a finding — stale
  waivers hide future regressions.

Each tool contributes only its AST visitor and rule table;
:func:`apply_waivers` turns raw visitor output + source text into the
final finding list, and :func:`report` renders the shared JSON shape
(sorted, byte-identical across runs — the linters obey the contract
they enforce).
"""

from __future__ import annotations

import dataclasses
import pathlib
import re
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple


@dataclasses.dataclass(frozen=True)
class Finding:
    """One sanitizer hit. ``waived`` marks a justified (reasoned)
    waiver; unwaived findings are the failures."""

    path: str
    line: int
    col: int
    rule: str
    message: str
    waived: bool = False
    waiver_reason: str = ""

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: "
                f"[{self.rule}] {self.message}")


@dataclasses.dataclass
class Waiver:
    line: int
    rules: Tuple[str, ...]
    reason: str
    used: bool = False


def waiver_pattern(tool: str) -> "re.Pattern[str]":
    """The per-line waiver comment for ``tool``:
    ``# <tool>: ok(rules) -- reason``."""
    return re.compile(
        r"#\s*" + re.escape(tool)
        + r":\s*ok\(([^)]*)\)(?:\s*--\s*(\S.*\S|\S))?")


def parse_waivers(source: str, tool: str, rules: Sequence[str]
                  ) -> Tuple[Dict[int, Waiver], List[Finding]]:
    """Line -> waiver, plus findings for malformed waivers. A waiver
    on a comment-only line covers the next line instead."""
    pattern = waiver_pattern(tool)
    waivers: Dict[int, Waiver] = {}
    bad: List[Finding] = []
    for lineno, text in enumerate(source.splitlines(), start=1):
        m = pattern.search(text)
        if not m:
            continue
        named = tuple(sorted(
            r.strip() for r in m.group(1).split(",") if r.strip()))
        reason = (m.group(2) or "").strip()
        target = (lineno + 1
                  if text.lstrip().startswith("#") else lineno)
        unknown = [r for r in named if r not in rules]
        if unknown:
            bad.append(Finding(
                "", lineno, m.start(), "waiver",
                f"waiver names unknown rule(s) "
                f"{', '.join(unknown)}"))
        if not reason:
            bad.append(Finding(
                "", lineno, m.start(), "waiver",
                "waiver without a reason — append "
                "'-- <why this is safe>'"))
        waivers[target] = Waiver(lineno, named, reason)
    return waivers, bad


def apply_waivers(raw: Sequence[Finding], source: str, path: str,
                  tool: str, rules: Sequence[str]) -> List[Finding]:
    """Fold the source's waiver comments into the visitor's raw
    findings: matching reasoned waivers mark findings ``waived``,
    malformed and stale waivers become findings of their own, and the
    result is sorted (path, line, col, rule) — deterministic output
    for byte-identical lint reports."""
    waivers, bad = parse_waivers(source, tool, rules)
    out: List[Finding] = []
    for f in raw:
        w = waivers.get(f.line)
        if w is not None and f.rule in w.rules:
            w.used = True
            out.append(dataclasses.replace(
                f, waived=bool(w.reason), waiver_reason=w.reason))
        else:
            out.append(f)
    for f in bad:
        out.append(dataclasses.replace(f, path=path))
    for w in waivers.values():
        if not w.used:
            out.append(Finding(
                path, w.line, 0, "waiver",
                "waiver matches no finding on its line — stale "
                "waivers hide future regressions; delete it"))
    out.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return out


def iter_py_files(paths: Sequence[str]) -> List[str]:
    """Every ``*.py`` under ``paths`` (dirs recursed, sorted,
    ``__pycache__`` skipped) — the shared file walk, so both tools
    lint the identical tree."""
    files: List[str] = []
    for p in paths:
        path = pathlib.Path(p)
        if path.is_dir():
            files.extend(
                str(f) for f in sorted(path.rglob("*.py"))
                if "__pycache__" not in f.parts)
        elif path.suffix == ".py":
            files.append(str(path))
    return sorted(set(files))


def lint_paths(paths: Sequence[str],
               lint_source: Callable[[str, str], List[Finding]]
               ) -> List[Finding]:
    findings: List[Finding] = []
    for fname in iter_py_files(paths):
        with open(fname, encoding="utf-8") as fh:
            findings.extend(lint_source(fh.read(), fname))
    return findings


def report(findings: Iterable[Finding], rules: Sequence[str],
           files: Optional[int] = None) -> dict:
    """JSON-able summary: unwaived findings are the failures; waived
    ones are counted (bench tracks waiver growth)."""
    unwaived = [f for f in findings if not f.waived]
    waived = [f for f in findings if f.waived]
    by_rule: Dict[str, int] = {}
    for f in unwaived:
        by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
    waived_by_rule: Dict[str, int] = {}
    for f in waived:
        waived_by_rule[f.rule] = waived_by_rule.get(f.rule, 0) + 1
    out = {
        "findings": [f.as_dict() for f in unwaived],
        "findings_by_rule": by_rule,
        "waived": len(waived),
        "waived_by_rule": waived_by_rule,
        "rules": list(rules),
        "ok": not unwaived,
    }
    if files is not None:
        out["files"] = files
    return out
