"""Determinism tooling — the machine-checked replay contract.

Every simulator layer (chaos -> fleet -> sched -> health -> globe)
stakes its correctness on one invariant: *same seed => byte-identical
event log and report*. This package is the tooling that defends it:

* :mod:`~kind_tpu_sim.analysis.knobs` — the registry every
  ``KIND_TPU_SIM_*`` env read goes through; ``docs/KNOBS.md`` is
  generated from it, so no knob ships undocumented.
* :mod:`~kind_tpu_sim.analysis.detlint` — an AST-based static checker
  that flags determinism hazards (wall-clock reads, unseeded entropy,
  unordered iteration, unsorted JSON, import-time env reads,
  unregistered knobs) with per-line ``detlint: ok(rule) -- reason``
  comment waivers.
* :mod:`~kind_tpu_sim.analysis.replaycheck` — a runtime sanitizer that
  runs a scenario twice under the same seed, hashes the event stream
  incrementally, and bisects a mismatch to the first divergent event.

CLI: ``kind-tpu-sim analysis lint|knobs|replay`` (docs: README
"The determinism contract", docs/ARCHITECTURE.md).

``knobs`` is imported eagerly (the low-level layers need it);
``detlint``/``replaycheck`` load lazily so the hot runtime import path
doesn't pay for the tooling.
"""

from __future__ import annotations

import importlib

from kind_tpu_sim.analysis import knobs  # noqa: F401  (eager: low-level dep)

_LAZY = ("detlint", "replaycheck")


def __getattr__(name: str):
    if name in _LAZY:
        return importlib.import_module(f"kind_tpu_sim.analysis.{name}")
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(list(globals()) + list(_LAZY))
