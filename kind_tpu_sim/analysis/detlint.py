"""detlint — the determinism sanitizer (static AST pass).

Every simulator layer promises *same seed => byte-identical report*,
and every hazard that has ever broken that promise in an LLM-serving
or cluster simulator is a one-liner: a stray ``time.time()``, an
unseeded ``random.choice``, iteration over a ``set``, a ``json.dumps``
without ``sort_keys``, an env read at import time. Example-based
replay tests only catch the code paths they happen to cross; detlint
walks the AST of the whole package and flags the hazard *class*:

=================  ===================================================
``wallclock``      ``time.time/monotonic/perf_counter`` /
                   ``datetime.now`` outside the blessed measurement
                   allowlist — virtual-clock code must never read the
                   wall (``VirtualClock`` is the sanctioned clock).
``entropy``        unseeded entropy: module-level ``random.*`` /
                   ``np.random.*`` calls, no-arg ``random.Random()``
                   / ``RandomState()`` / ``default_rng()``,
                   ``uuid.uuid4``, ``os.urandom``, ``secrets.*``.
                   (``jax.random`` is key-seeded and exempt.)
``set-iter``       ordered consumption of an unordered collection:
                   a set (literal, ``set()``, set ops) iterated /
                   listed / joined / summed without ``sorted(...)``.
``fs-order``       ``os.listdir`` / ``glob`` / ``Path.iterdir`` fed
                   to iteration without ``sorted(...)`` — filesystem
                   order is platform noise.
``json-sort``      ``json.dumps``/``json.dump`` without
                   ``sort_keys=True`` — unsorted keys are the classic
                   byte-identity breaker.
``env-import``     environment reads at import time (module or class
                   scope): config frozen at import order, invisible
                   to replays.
``knob-env``       a ``KIND_TPU_SIM_*`` env var read directly instead
                   of through :mod:`~kind_tpu_sim.analysis.knobs`.
``heap-order``     ``heapq.heappush`` of a tuple without an integer
                   tiebreaker before the payload — when two entries
                   tie on the leading keys the comparison falls
                   through to the payload (nondeterministic pop
                   order, or a TypeError at the worst moment); push
                   ``(time, lane, seq, payload)`` like
                   :class:`~kind_tpu_sim.fleet.events.EventHeap`.
``unknown-knob``   a ``KIND_TPU_SIM_*`` token (code, help text, or
                   docstring) that the knob registry doesn't know —
                   the undocumented-knob guard.
``waiver``         a malformed waiver: missing reason, unknown rule
                   name, or a waiver that matches no finding.
=================  ===================================================

Waivers are per-line and must carry a reason::

    t0 = time.monotonic()  [hash]detlint: ok(wallclock) -- real-time bench

(with ``#`` for ``[hash]``; the comment may also sit alone on the
line directly above). A reasonless waiver is itself a finding — the
contract is *fix or justify*, never silence.

Run it: ``kind-tpu-sim analysis lint kind_tpu_sim`` (wired into
pre-commit and CI); the JSON output is sorted-keys and byte-identical
across runs, like every other subcommand. The finding/waiver/report
machinery is shared with **contractlint** (the interface-contract
sanitizer) through :mod:`~kind_tpu_sim.analysis.lintcore`.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, List, Optional, Sequence, Tuple

from kind_tpu_sim.analysis import knobs, lintcore
from kind_tpu_sim.analysis.lintcore import Finding

RULES = (
    "wallclock", "entropy", "set-iter", "fs-order", "json-sort",
    "env-import", "knob-env", "heap-order", "unknown-knob", "waiver",
)

# heapq entry points whose pushed tuples need a tiebreaker
_HEAP_PUSH_FNS = frozenset(("heappush", "heappushpop", "heapreplace"))

# Files where wall-clock reads are the *point* — the real-time
# measurement layers whose outputs are wall timings by design and
# never feed a seeded report. Everything else justifies each read
# with a per-line waiver.
WALLCLOCK_ALLOW = (
    "kind_tpu_sim/profiling.py",        # the stopwatch layer
    "kind_tpu_sim/utils/worker_pool.py",  # subprocess IO deadlines
)

# The registry module declares knob names as literals; exempt it from
# the knob rules it implements.
KNOBS_MODULE = "kind_tpu_sim/analysis/knobs.py"

_TIME_FNS = frozenset((
    "time", "time_ns", "monotonic", "monotonic_ns", "perf_counter",
    "perf_counter_ns", "process_time", "process_time_ns",
))
_DATETIME_NAMES = frozenset(("datetime", "date", "_datetime"))
_DATETIME_FNS = frozenset(("now", "utcnow", "today"))

_RANDOM_MODULE_FNS = frozenset((
    "random", "randint", "randrange", "choice", "choices", "shuffle",
    "sample", "uniform", "gauss", "normalvariate", "lognormvariate",
    "expovariate", "betavariate", "gammavariate", "paretovariate",
    "vonmisesvariate", "weibullvariate", "triangular", "getrandbits",
    "randbytes", "seed",
))
_NP_RANDOM_FNS = frozenset((
    "rand", "randn", "randint", "random", "random_sample", "ranf",
    "choice", "shuffle", "permutation", "uniform", "normal",
    "standard_normal", "exponential", "poisson", "seed", "sample",
    "bytes",
))
_NP_NAMES = frozenset(("np", "numpy", "jnp"))
_SET_OP_METHODS = frozenset((
    "union", "intersection", "difference", "symmetric_difference",
))
_FS_CALLS = {
    ("os", "listdir"), ("os", "scandir"), ("os", "walk"),
    ("glob", "glob"), ("glob", "iglob"),
}
_FS_PATH_METHODS = frozenset(("iterdir", "glob", "rglob"))
# iteration sinks where source order becomes output order
_ORDER_SINK_NAMES = frozenset(("list", "tuple", "sum", "enumerate"))

_KNOB_TOKEN = re.compile(r"KIND_TPU_SIM_[A-Z0-9_]+")


def _dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class _Visitor(ast.NodeVisitor):
    def __init__(self, path: str, norm: str):
        self.path = path
        self.norm = norm            # posix-normalized, for allowlists
        self.out: List[Finding] = []
        self._func_depth = 0
        self.is_knobs = norm.endswith(KNOBS_MODULE)

    # -- helpers ------------------------------------------------------

    def _emit(self, node: ast.AST, rule: str, message: str) -> None:
        self.out.append(Finding(
            self.path, getattr(node, "lineno", 1),
            getattr(node, "col_offset", 0), rule, message))

    def _allow_wallclock(self) -> bool:
        return any(self.norm.endswith(a) for a in WALLCLOCK_ALLOW)

    # -- scope tracking (env-import) ----------------------------------

    def visit_FunctionDef(self, node):      # noqa: N802
        self._func_depth += 1
        self.generic_visit(node)
        self._func_depth -= 1

    def visit_AsyncFunctionDef(self, node):  # noqa: N802
        self.visit_FunctionDef(node)

    def visit_Lambda(self, node):           # noqa: N802
        self._func_depth += 1
        self.generic_visit(node)
        self._func_depth -= 1

    # -- expression rules ---------------------------------------------

    def visit_Attribute(self, node):        # noqa: N802
        dotted = _dotted(node)
        if dotted and not self._allow_wallclock():
            base, _, attr = dotted.rpartition(".")
            if base in ("time", "_time") and attr in _TIME_FNS:
                self._emit(node, "wallclock",
                           f"wall-clock read {dotted}() — virtual-"
                           "clock code must take a clock parameter "
                           "(VirtualClock) or be allowlisted")
            elif (attr in _DATETIME_FNS
                  and base.rpartition(".")[2] in _DATETIME_NAMES):
                self._emit(node, "wallclock",
                           f"wall-clock read {dotted}()")
        if dotted == "os.environ" and self._func_depth == 0:
            self._emit(node, "env-import",
                       "os.environ read at import time — resolve "
                       "inside a function (or through analysis.knobs "
                       "at call time)")
        self.generic_visit(node)

    def visit_Call(self, node):             # noqa: N802
        dotted = _dotted(node.func) or ""
        base, _, attr = dotted.rpartition(".")

        # entropy ------------------------------------------------------
        if base == "random" and attr in _RANDOM_MODULE_FNS:
            self._emit(node, "entropy",
                       f"unseeded module-level {dotted}() — use a "
                       "seeded random.Random instance")
        elif dotted in ("random.Random", "numpy.random.RandomState",
                        "np.random.RandomState",
                        "numpy.random.default_rng",
                        "np.random.default_rng") \
                and not node.args and not node.keywords:
            self._emit(node, "entropy",
                       f"{dotted}() without a seed draws OS entropy")
        elif dotted in ("random.SystemRandom", "os.urandom",
                        "uuid.uuid4") or base == "secrets":
            self._emit(node, "entropy",
                       f"{dotted}() is inherently nondeterministic")
        elif (base.rpartition(".")[0] in _NP_NAMES
              and base.rpartition(".")[2] == "random"
              and attr in _NP_RANDOM_FNS):
            self._emit(node, "entropy",
                       f"unseeded module-level {dotted}() — use a "
                       "seeded Generator/RandomState")

        # json-sort ----------------------------------------------------
        if base in ("json", "_json") and attr in ("dumps", "dump"):
            has_dynamic = any(kw.arg is None for kw in node.keywords)
            sorted_kw = any(
                kw.arg == "sort_keys"
                and isinstance(kw.value, ast.Constant)
                and kw.value.value is True
                for kw in node.keywords)
            if not sorted_kw and not has_dynamic:
                self._emit(node, "json-sort",
                           f"{dotted}() without sort_keys=True — "
                           "unsorted keys break byte-identity")

        # heap-order ---------------------------------------------------
        if (base in ("heapq", "_heapq") and attr in _HEAP_PUSH_FNS
                and len(node.args) >= 2
                and isinstance(node.args[1], ast.Tuple)
                and len(node.args[1].elts) < 3):
            self._emit(node, "heap-order",
                       f"{dotted}() of a {len(node.args[1].elts)}-"
                       "tuple: with no integer tiebreaker before the "
                       "payload, equal keys compare the payloads — "
                       "nondeterministic pop order; push (time, "
                       "lane, seq, payload) (fleet/events.EventHeap)")

        # env reads ----------------------------------------------------
        if dotted in ("os.getenv", "os.environ.get"):
            if self._func_depth == 0:
                self._emit(node, "env-import",
                           "environment read at import time")
            self._check_knob_read(node)
        elif attr == "get" and node.args:
            # env.get("KIND_TPU_SIM_*") through any alias
            self._check_knob_read(node)

        # order sinks over unordered sources ---------------------------
        if isinstance(node.func, ast.Name) \
                and node.func.id in _ORDER_SINK_NAMES and node.args:
            self._check_order(node.args[0],
                              f"{node.func.id}(...)")
        elif isinstance(node.func, ast.Attribute) \
                and node.func.attr == "join" and node.args:
            # catches '","'.join(...) too (dotted name is None for a
            # str-literal receiver)
            self._check_order(node.args[0], "str.join(...)")

        self.generic_visit(node)

    def _check_knob_read(self, node: ast.Call) -> None:
        if self.is_knobs or not node.args:
            return
        arg = node.args[0]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str) \
                and arg.value.startswith(knobs.PREFIX):
            self._emit(node, "knob-env",
                       f"direct env read of {arg.value} — go through "
                       "kind_tpu_sim.analysis.knobs.get()")

    def visit_Subscript(self, node):        # noqa: N802
        if _dotted(node.value) == "os.environ":
            if self._func_depth == 0:
                self._emit(node, "env-import",
                           "os.environ read at import time")
            key = node.slice
            if isinstance(key, ast.Constant) \
                    and isinstance(key.value, str) \
                    and key.value.startswith(knobs.PREFIX) \
                    and not self.is_knobs:
                self._emit(node, "knob-env",
                           f"direct env read of {key.value} — go "
                           "through analysis.knobs.get()")
        self.generic_visit(node)

    # -- iteration order ----------------------------------------------

    def _is_unordered(self, node: ast.AST) -> Tuple[bool, str]:
        if isinstance(node, (ast.Set, ast.SetComp)):
            return True, "a set"
        if isinstance(node, ast.Call):
            d = _dotted(node.func) or ""
            if d in ("set", "frozenset"):
                return True, f"{d}(...)"
            _, _, attr = d.rpartition(".")
            if attr in _SET_OP_METHODS:
                return True, f".{attr}(...)"
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _FS_PATH_METHODS:
                return True, f".{node.func.attr}()"
            b, _, a = d.rpartition(".")
            if (b, a) in _FS_CALLS:
                return True, f"{d}()"
        if isinstance(node, ast.BinOp) and isinstance(
                node.op, (ast.BitOr, ast.BitAnd, ast.BitXor)):
            for side in (node.left, node.right):
                hit, what = self._is_unordered(side)
                if hit:
                    return True, what
        return False, ""

    def _check_order(self, source: ast.AST, sink: str) -> None:
        hit, what = self._is_unordered(source)
        if not hit:
            return
        rule = ("fs-order" if "dir" in what or "glob" in what
                or "walk" in what else "set-iter")
        self._emit(source, rule,
                   f"{sink} consumes {what} without sorted(...) — "
                   "unordered iteration reaching output breaks "
                   "byte-identity")

    def visit_For(self, node):              # noqa: N802
        self._check_order(node.iter, "for-loop")
        self.generic_visit(node)

    def _visit_comp(self, node):
        for gen in node.generators:
            self._check_order(gen.iter, "comprehension")
        self.generic_visit(node)

    visit_ListComp = _visit_comp            # noqa: N815
    visit_DictComp = _visit_comp            # noqa: N815
    visit_GeneratorExp = _visit_comp        # noqa: N815

    def visit_SetComp(self, node):          # noqa: N802
        # building a set is order-free; only its consumption matters
        self.generic_visit(node)

    # -- knob tokens in strings ---------------------------------------

    def visit_Constant(self, node):         # noqa: N802
        if isinstance(node.value, str) and not self.is_knobs:
            for match in _KNOB_TOKEN.finditer(node.value):
                token = match.group(0)
                if knobs.is_registered(token):
                    continue
                if token.endswith("_") and any(
                        name.startswith(token)
                        for name in knobs.REGISTRY):
                    continue  # prefix reference, e.g. FOO_* in docs
                self._emit(node, "unknown-knob",
                           f"{token} is not in the knob registry "
                           "(kind_tpu_sim/analysis/knobs.py) — "
                           "register it or fix the name")
        self.generic_visit(node)


def lint_source(source: str, path: str = "<string>"
                ) -> List[Finding]:
    """All findings (waived ones included, marked) for one module."""
    norm = path.replace("\\", "/")
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as exc:
        return [Finding(path, exc.lineno or 1, exc.offset or 0,
                        "syntax", f"syntax error: {exc.msg}")]
    visitor = _Visitor(path, norm)
    visitor.visit(tree)
    # a module-level os.environ.get() trips both the Call and the
    # inner Attribute check — one finding per (line, rule) is enough
    seen = set()
    raw: List[Finding] = []
    for f in visitor.out:
        key = (f.line, f.col, f.rule)
        if key in seen:
            continue
        dup = (f.line, f.rule)
        if f.rule == "env-import" and dup in seen:
            continue
        seen.add(key)
        seen.add(dup if f.rule == "env-import" else key)
        raw.append(f)
    return lintcore.apply_waivers(raw, source, path, "detlint",
                                  RULES)


def iter_py_files(paths: Sequence[str]) -> List[str]:
    return lintcore.iter_py_files(paths)


def lint_paths(paths: Sequence[str]) -> List[Finding]:
    return lintcore.lint_paths(paths, lint_source)


def report(findings: Iterable[Finding],
           files: Optional[int] = None) -> dict:
    """JSON-able summary: unwaived findings are the failures; waived
    ones are counted (bench tracks waiver growth)."""
    return lintcore.report(findings, RULES, files)
