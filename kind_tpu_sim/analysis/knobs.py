"""The knob registry — every ``KIND_TPU_SIM_*`` env var, in one place.

The simulator's determinism contract (docs/ARCHITECTURE.md) makes the
report a pure function of *(config, seed)* — and env knobs are config.
A knob read scattered as a bare ``os.environ.get("KIND_TPU_SIM_...")``
is config the replay can't see: it ships undocumented, its parse
semantics drift per call site, and a typo'd name fails silently to its
default. So every ``KIND_TPU_SIM_*`` read goes through this registry:

* :data:`REGISTRY` declares each knob once — name, typed default,
  owning layer, one-line doc. ``docs/KNOBS.md`` is *generated* from it
  (:func:`render_markdown`), so no knob can ship undocumented, and the
  generated page is CI-checked against the registry
  (``kind-tpu-sim analysis knobs --check-docs``).
* :func:`get` / :func:`get_raw` are the only sanctioned read paths;
  **detlint** (analysis/detlint.py, rule ``knob-env``) flags any direct
  ``os.environ`` read of a ``KIND_TPU_SIM_*`` key elsewhere, and rule
  ``unknown-knob`` flags any ``KIND_TPU_SIM_*`` token — in code, help
  text, or docs — that this registry doesn't know.

Parse semantics are uniform and forgiving, matching the historical
per-module ``resolve_*`` helpers: a missing or unparseable value falls
back to the default (a typo'd knob must degrade, never crash a run),
and booleans treat ``"" / "0" / "false" / "no"`` (case-insensitive) as
false, anything else as true.

This module is imported by the lowest layers (utils/shell.py, the
worker-pool child) — it must stay stdlib-only and import nothing from
``kind_tpu_sim``.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, List, Optional

PREFIX = "KIND_TPU_SIM_"

# Values a bool knob reads as OFF; everything else non-missing is ON.
FALSE_VALUES = ("", "0", "false", "no")


@dataclasses.dataclass(frozen=True)
class Knob:
    """One registered env knob. ``default`` is already typed (or None
    for no-default knobs like CACHE_DIR); ``kind`` drives parsing."""

    name: str
    default: object
    kind: str          # "int" | "float" | "bool" | "str"
    layer: str         # owning subsystem (docs/KNOBS.md grouping)
    description: str

    def parse(self, raw: Optional[str]) -> object:
        """Typed value of ``raw``; missing/unparseable -> default."""
        if raw is None:
            return self.default
        if self.kind == "bool":
            return raw.lower() not in FALSE_VALUES
        if self.kind == "int":
            try:
                return int(raw)
            except ValueError:
                return self.default
        if self.kind == "float":
            try:
                return float(raw)
            except ValueError:
                return self.default
        return raw


REGISTRY: Dict[str, Knob] = {}


def _register(name: str, default: object, kind: str, layer: str,
              description: str) -> str:
    if not name.startswith(PREFIX):
        raise ValueError(f"knob {name!r} lacks the {PREFIX} prefix")
    if name in REGISTRY:
        raise ValueError(f"knob {name!r} registered twice")
    REGISTRY[name] = Knob(name, default, kind, layer, description)
    return name


# -- the declarations (docs/KNOBS.md is generated from this table) ----

# runtime / warm path (docs/PERFORMANCE.md)
CACHE_DIR = _register(
    "KIND_TPU_SIM_CACHE_DIR", None, "str", "runtime",
    "Persistent XLA compilation-cache directory "
    "(default: `<repo>/.cache/jax`).")
NO_COMPILATION_CACHE = _register(
    "KIND_TPU_SIM_NO_COMPILATION_CACHE", False, "bool", "runtime",
    "Disable the persistent XLA compilation cache entirely.")
POOL_WARM = _register(
    "KIND_TPU_SIM_POOL_WARM", False, "bool", "runtime",
    "Worker-pool child warms up (jax import + backend init + psum "
    "compile) before answering; set by `WorkerPool(warm=True)`.")
MAX_RETRIES = _register(
    "KIND_TPU_SIM_MAX_RETRIES", 3, "int", "runtime",
    "Retries for transiently-failing external commands "
    "(classified retry, docs/CHAOS.md).")
RETRY_BASE_MS = _register(
    "KIND_TPU_SIM_RETRY_BASE_MS", 50.0, "float", "runtime",
    "Base backoff in ms; doubles per retry with full jitter on top.")
CMD_TIMEOUT_S = _register(
    "KIND_TPU_SIM_CMD_TIMEOUT_S", None, "float", "runtime",
    "Per-command wall deadline in seconds (unset: no cap); an "
    "overrunning command is killed and classified transient.")

# parallel / kernels
RING_DOUBLE_BUFFER = _register(
    "KIND_TPU_SIM_RING_DOUBLE_BUFFER", True, "bool", "parallel",
    "Double-buffered ring-attention K/V rotation; `0` restores the "
    "serial rotate-then-compute ordering.")

# chaos (docs/CHAOS.md)
CHAOS_SEED = _register(
    "KIND_TPU_SIM_CHAOS_SEED", 0, "int", "chaos",
    "Fault-plan seed: the same seed replays the identical fault "
    "schedule (also pins retry-backoff jitter).")
CHAOS_FAULT = _register(
    "KIND_TPU_SIM_CHAOS_FAULT", None, "str", "chaos",
    "Injected worker fault spec: `crash@N`, `hang@N:S`, `slow@N:S`, "
    "or `flaky@K:S` (docs/CHAOS.md, docs/HEALTH.md).")

# fleet (docs/FLEET.md)
FLEET_SEED = _register(
    "KIND_TPU_SIM_FLEET_SEED", 0, "int", "fleet",
    "Workload seed for the fleet simulator: same seed, "
    "byte-identical report.")
FLEET_TICK_S = _register(
    "KIND_TPU_SIM_FLEET_TICK_S", 0.01, "float", "fleet",
    "Virtual scheduling quantum in seconds.")
FLEET_FF = _register(
    "KIND_TPU_SIM_FLEET_FF", True, "bool", "fleet",
    "Idle-gap fast-forward (replay-identical); `0` forces the plain "
    "per-tick loop.")
FLEET_WARMUP_S = _register(
    "KIND_TPU_SIM_FLEET_WARMUP_S", 0.55, "float", "fleet",
    "Modeled replica warm-up in virtual seconds (default: the "
    "measured warm bring-up, docs/PERFORMANCE.md).")
FLEET_EVENT_CORE = _register(
    "KIND_TPU_SIM_FLEET_EVENT_CORE", True, "bool", "fleet",
    "Event-heap simulation core: the fleet/globe drivers step only "
    "the tick boundaries where an event lands (replay-identical); "
    "`0` forces the plain per-tick loop.")
FLEET_COLUMNAR = _register(
    "KIND_TPU_SIM_FLEET_COLUMNAR", True, "bool", "fleet",
    "Columnar (struct-of-arrays) replica state for all-analytic "
    "fleets: wake scans, tick fan-out, and least-outstanding "
    "routing run over numpy arrays instead of per-object scans "
    "(replay-identical); `0` reverts to the per-object paths.")
POOL_SHM = _register(
    "KIND_TPU_SIM_POOL_SHM", True, "bool", "runtime",
    "Worker-pool bulk transport over multiprocessing shared_memory "
    "segments (length-prefixed JSON stays for control frames); `0` "
    "reverts every payload to the in-band pipe framing.")
POOL_SHM_SEGS = _register(
    "KIND_TPU_SIM_POOL_SHM_SEGS", "", "str", "runtime",
    "INTERNAL: `parent_to_worker:worker_to_parent` shared-memory "
    "segment names a pool parent hands its protocol worker at "
    "spawn; never set by hand — the parent owns segment lifetime.")
GLOBE_SHARDS = _register(
    "KIND_TPU_SIM_GLOBE_SHARDS", 0, "int", "globe",
    "Default worker-shard count for the globe driver: cells "
    "partition across N pool workers with conservative time "
    "windows and a deterministic merge (replay-identical); 0 runs "
    "the single-process lockstep loop.")

# disaggregated prefill/decode serving (docs/DISAGG.md)
DISAGG_TIER = _register(
    "KIND_TPU_SIM_DISAGG_TIER", "ici", "str", "disagg",
    "Default fabric the prefill->decode KV-cache handoff crosses: "
    "`ici` (same-pod interconnect) or `dcn` (cross-pod network); "
    "bandwidths come from the collectives tier table.")
DISAGG_DTYPE = _register(
    "KIND_TPU_SIM_DISAGG_DTYPE", "bf16", "str", "disagg",
    "Default decode arithmetic (`bf16` or `int8`) for calibrated "
    "disagg replicas — picks the decode roofline point and the "
    "KV-cache bytes-per-token.")
CALIBRATION = _register(
    "KIND_TPU_SIM_CALIBRATION", None, "str", "disagg",
    "Path to a cost-model calibration file (default: the checked-in "
    "`kind_tpu_sim/fleet/calibration/r05.json`); regenerate with "
    "`kind-tpu-sim fleet calibrate`.")

# sched (docs/SCHED.md)
SCHED_SEED = _register(
    "KIND_TPU_SIM_SCHED_SEED", 0, "int", "sched",
    "Workload seed for the scheduler simulator: same seed, "
    "byte-identical event log.")

# training tenant (docs/TRAINING.md)
TRAIN_CKPT_EVERY = _register(
    "KIND_TPU_SIM_TRAIN_CKPT_EVERY", 0, "int", "train",
    "Default checkpoint cadence in steps for training gangs; `0` "
    "derives the Young-Daly optimum from the gang's step time, "
    "checkpoint write cost, and the assumed preemption MTBF.")
TRAIN_CKPT_WRITE_S = _register(
    "KIND_TPU_SIM_TRAIN_CKPT_WRITE_S", 0.05, "float", "train",
    "Modeled cost of one checkpoint write in virtual seconds (the "
    "gang pauses stepping for this long at each cadence boundary).")
TRAIN_RESTART_S = _register(
    "KIND_TPU_SIM_TRAIN_RESTART_S", 0.2, "float", "train",
    "Modeled resume cost in virtual seconds after a reschedule or "
    "elastic resize (checkpoint load + re-init collectives).")
TRAIN_MTBF_S = _register(
    "KIND_TPU_SIM_TRAIN_MTBF_S", 60.0, "float", "train",
    "Assumed mean time between preemptions in virtual seconds — "
    "the rate the Young-Daly auto-cadence and the checkpoint-"
    "economics report price lost work against.")
TRAIN_ELASTIC = _register(
    "KIND_TPU_SIM_TRAIN_ELASTIC", True, "bool", "train",
    "Allow elastic gangs to grow onto scavenged/spot capacity and "
    "shrink (never abort) on reclaim; `0` pins every gang at its "
    "base topology.")

# globe (docs/GLOBE.md)
GLOBE_SEED = _register(
    "KIND_TPU_SIM_GLOBE_SEED", 0, "int", "globe",
    "Workload seed for the fleet-of-fleets simulator; per-zone "
    "traces derive sub-seeds from it.")

# overload containment (docs/OVERLOAD.md)
OVERLOAD_RETRY_BUDGET = _register(
    "KIND_TPU_SIM_OVERLOAD_RETRY_BUDGET", 0.1, "float", "overload",
    "Client retry-budget earn ratio: budget tokens earned per "
    "admitted first-attempt request; `0` disables the budget "
    "(retries unbounded — the controls-off storm mode).")
OVERLOAD_HEDGE_QUANTILE = _register(
    "KIND_TPU_SIM_OVERLOAD_HEDGE_QUANTILE", 0.95, "float",
    "overload",
    "Latency quantile the hedge delay is derived from: a hedge "
    "fires only after the primary has been in flight longer than "
    "this quantile of observed service times.")
OVERLOAD_BREAKER_WINDOW = _register(
    "KIND_TPU_SIM_OVERLOAD_BREAKER_WINDOW", 16, "int", "overload",
    "Rolling outcome-window length of the per-replica / per-cell "
    "circuit breakers.")
OVERLOAD_BROWNOUT = _register(
    "KIND_TPU_SIM_OVERLOAD_BROWNOUT", True, "bool", "overload",
    "Brownout ladder under sustained SLO breach (cap max_new, "
    "disable hedging, shed low tiers); `0` keeps replicas serving "
    "full requests all the way into queue collapse.")

# multi-tenancy (docs/TENANCY.md)
TENANT_ISOLATION = _register(
    "KIND_TPU_SIM_TENANT_ISOLATION", True, "bool", "tenant",
    "Tenant isolation machinery (admission quotas, deficit-round-"
    "robin queuing, decode-pool KV budgets) on tenancy-declaring "
    "runs; `0` keeps the tenant traffic model but serves it FCFS "
    "and unmetered — the noisy-neighbor contrast mode.")
TENANT_DRR_QUANTUM = _register(
    "KIND_TPU_SIM_TENANT_DRR_QUANTUM", 4.0, "float", "tenant",
    "Deficit-round-robin quantum: requests credited per router "
    "visit per unit of tenant weight (larger = coarser fairness, "
    "fewer tenant switches).")

# model zoo / generations (docs/ZOO.md)
GENERATION = _register(
    "KIND_TPU_SIM_GENERATION", "v5e", "str", "zoo",
    "Default accelerator generation replicas price against when a "
    "run declares none (`v5e` / `v4` / `v5p`); each name resolves "
    "to a checked-in `fleet/calibration/<gen>.json`.")
ZOO_MODELS = _register(
    "KIND_TPU_SIM_ZOO_MODELS", 3, "int", "zoo",
    "Default model count `fleet run --zoo` serves (clamped to the "
    "checked-in default zoo's size).")
ZOO_SWAP_FACTOR = _register(
    "KIND_TPU_SIM_ZOO_SWAP_FACTOR", 1.0, "float", "zoo",
    "Multiplier on the modeled weight-load (model swap) time — the "
    "calibration's HBM-bandwidth load priced up for checkpoint "
    "parse/reshard overhead; `0` makes every swap free.")

# health / gray-failure detection (docs/HEALTH.md)
HEALTH_ALPHA = _register(
    "KIND_TPU_SIM_HEALTH_ALPHA", 0.25, "float", "health",
    "EWMA weight of the detector's global latency baseline.")
HEALTH_SUSPECT_PHI = _register(
    "KIND_TPU_SIM_HEALTH_SUSPECT_PHI", 2.0, "float", "health",
    "Phi-accrual suspicion level marking a component suspect.")
HEALTH_QUARANTINE_PHI = _register(
    "KIND_TPU_SIM_HEALTH_QUARANTINE_PHI", 8.0, "float", "health",
    "Phi level a suspicious sample must reach to count toward "
    "quarantine.")
HEALTH_QUARANTINE_EVALS = _register(
    "KIND_TPU_SIM_HEALTH_QUARANTINE_EVALS", 3, "int", "health",
    "Consecutive suspicious samples escalating suspect -> "
    "quarantined (no-flap hysteresis).")
HEALTH_PROBE_OK = _register(
    "KIND_TPU_SIM_HEALTH_PROBE_OK", 2, "int", "health",
    "Clean probes required to lift a quarantine.")
HEALTH_PROBE_INTERVAL_S = _register(
    "KIND_TPU_SIM_HEALTH_PROBE_INTERVAL_S", 0.25, "float", "health",
    "Seconds between probes of a quarantined component.")
HEALTH_MIN_SAMPLES = _register(
    "KIND_TPU_SIM_HEALTH_MIN_SAMPLES", 4, "int", "health",
    "Baseline samples required before suspicion can fire.")
HEALTH_SIGMA_FRAC = _register(
    "KIND_TPU_SIM_HEALTH_SIGMA_FRAC", 0.1, "float", "health",
    "Sigma floor as a fraction of the baseline mean (keeps jitter "
    "on a near-constant baseline from reading as suspicion).")
HEALTH_SIGMA_ABS = _register(
    "KIND_TPU_SIM_HEALTH_SIGMA_ABS", 1e-4, "float", "health",
    "Absolute sigma floor in seconds.")
HEALTH_PROBE_TIMEOUT_S = _register(
    "KIND_TPU_SIM_HEALTH_PROBE_TIMEOUT_S", 2.0, "float", "health",
    "Worker-grid probe deadline; a slower probe is a failed probe.")
HEALTH_SPEC_RATIO = _register(
    "KIND_TPU_SIM_HEALTH_SPEC_RATIO", 3.0, "float", "health",
    "In-flight cell age (x expected service time) that triggers "
    "speculative re-dispatch.")

# silent data corruption (docs/SDC.md)
SDC_RATE = _register(
    "KIND_TPU_SIM_SDC_RATE", 0.4, "float", "sdc",
    "Default corrupt fraction of a defective chip (share of its "
    "work whose output CRC is silently wrong) when an `sdc_chip` "
    "fault draws no explicit parameter.")
SDC_AUDIT_FRAC = _register(
    "KIND_TPU_SIM_SDC_AUDIT_FRAC", 0.0, "float", "sdc",
    "Default sampled duplicate-compute audit fraction for serving "
    "fleets: this share of completed requests re-executes on a "
    "second replica and CRC-compares (audit copies are real "
    "occupancy); `0` disables the audit lane.")

# fuzz
FUZZ_BUDGET = _register(
    "KIND_TPU_SIM_FUZZ_BUDGET", 25, "int", "fuzz",
    "Default number of composed scenarios one `chaos fuzz` "
    "campaign draws and runs.")
FUZZ_SEED = _register(
    "KIND_TPU_SIM_FUZZ_SEED", 0, "int", "fuzz",
    "Default fuzz campaign seed; the whole report is a pure "
    "function of (budget, seed, max-faults).")
FUZZ_MAX_FAULTS = _register(
    "KIND_TPU_SIM_FUZZ_MAX_FAULTS", 4, "int", "fuzz",
    "Upper bound on concurrent fault kinds composed into one "
    "drawn scenario (each draws 2..max).")

# tune
TUNE_SEED = _register(
    "KIND_TPU_SIM_TUNE_SEED", 0, "int", "tune",
    "Default search-stream seed for `fleet tune` / `globe tune`; "
    "candidate i is drawn from its own crc32(seed, i) sub-stream, "
    "so the same seed replays the byte-identical search trace.")
TUNE_BUDGET = _register(
    "KIND_TPU_SIM_TUNE_BUDGET", 16, "int", "tune",
    "Default number of candidates one tune search draws and "
    "screens (successive halving keeps ~half for the full-trace "
    "final rung).")
TUNE_CHAOS_BUDGET = _register(
    "KIND_TPU_SIM_TUNE_CHAOS_BUDGET", 0, "int", "tune",
    "Default chaos-rescoring budget: finalists are re-scored under "
    "this many fuzzer-drawn fault schedules (0 = chaos mode off).")

# bench
SKIP_MODEL_BENCH = _register(
    "KIND_TPU_SIM_SKIP_MODEL_BENCH", False, "bool", "bench",
    "Skip the accelerator model pass in bench.py (operator opt-out "
    "on tunnel-less hosts).")
BENCH_SLOW = _register(
    "KIND_TPU_SIM_BENCH_SLOW", False, "bool", "bench",
    "Also capture the slow bench extras (the 1M-request 24h "
    "fleet_scale trace); off by default to keep bench runs short.")

# Display order of layers in docs/KNOBS.md — pipeline order, not
# alphabetical, so the page reads like the architecture diagram.
LAYER_ORDER = ("runtime", "parallel", "chaos", "fleet", "disagg",
               "sched", "train", "globe", "overload", "tenant",
               "zoo", "health", "sdc", "fuzz", "tune", "bench")

# Layer -> its doc page (links are relative to docs/, where the
# generated KNOBS.md lives).
LAYER_DOCS = {
    "runtime": "PERFORMANCE.md",
    "parallel": "PERFORMANCE.md",
    "chaos": "CHAOS.md",
    "fleet": "FLEET.md",
    "disagg": "DISAGG.md",
    "sched": "SCHED.md",
    "train": "TRAINING.md",
    "globe": "GLOBE.md",
    "overload": "OVERLOAD.md",
    "tenant": "TENANCY.md",
    "zoo": "ZOO.md",
    "health": "HEALTH.md",
    "sdc": "SDC.md",
    "fuzz": "FUZZ.md",
    "tune": "TUNE.md",
    "bench": "PERFORMANCE.md",
}


# -- the sanctioned read paths ----------------------------------------


def get_raw(name: str,
            environ: Optional[Dict[str, str]] = None) -> Optional[str]:
    """The raw env string of a REGISTERED knob (None when unset).
    Reading an unregistered name is a programming error — the exact
    silent-typo failure the registry exists to make loud."""
    if name not in REGISTRY:
        raise KeyError(f"unregistered knob {name!r}; declare it in "
                       "kind_tpu_sim/analysis/knobs.py")
    env = os.environ if environ is None else environ
    return env.get(name)


def get(name: str, environ: Optional[Dict[str, str]] = None) -> object:
    """Resolved, typed value of a registered knob: env > default,
    with unparseable values falling back to the default."""
    return REGISTRY[name].parse(get_raw(name, environ))


def resolve_all(environ: Optional[Dict[str, str]] = None
                ) -> Dict[str, object]:
    """Every knob's resolved value, by name — the `analysis knobs`
    CLI surface."""
    return {name: get(name, environ) for name in sorted(REGISTRY)}


def is_registered(name: str) -> bool:
    return name in REGISTRY


# -- docs generation (docs/KNOBS.md) ----------------------------------


def _fmt_default(knob: Knob) -> str:
    if knob.default is None:
        return "*(unset)*"
    if knob.kind == "bool":
        return "on" if knob.default else "off"
    return f"`{knob.default}`"


def render_markdown() -> str:
    """The full, deterministic text of docs/KNOBS.md. Regenerate with
    `kind-tpu-sim analysis knobs --write-docs`; CI verifies the file
    matches byte-for-byte (`--check-docs`)."""
    lines: List[str] = [
        "# Env knobs",
        "",
        "<!-- GENERATED FILE — do not edit by hand. -->",
        "<!-- Source of truth: kind_tpu_sim/analysis/knobs.py. -->",
        "<!-- Regenerate: python -m kind_tpu_sim analysis knobs"
        " --write-docs -->",
        "",
        "Every `KIND_TPU_SIM_*` environment variable the simulator",
        "reads, generated from the machine-readable registry",
        "(`kind_tpu_sim/analysis/knobs.py`). Knob reads anywhere else",
        "in the tree are rejected by the determinism sanitizer",
        "(`kind-tpu-sim analysis lint`, rule `knob-env`), so this page",
        "is complete by construction. Booleans read `` ``/`0`/`false`/",
        "`no` as off and anything else as on; an unparseable value",
        "falls back to the default.",
        "",
    ]
    for layer in LAYER_ORDER:
        knobs = sorted((k for k in REGISTRY.values()
                        if k.layer == layer), key=lambda k: k.name)
        if not knobs:
            continue
        lines.append(f"## {layer} — see [{LAYER_DOCS[layer]}]"
                     f"({LAYER_DOCS[layer]})")
        lines.append("")
        lines.append("| Knob | Default | Purpose |")
        lines.append("| --- | --- | --- |")
        for knob in knobs:
            lines.append(f"| `{knob.name}` | {_fmt_default(knob)} "
                         f"| {knob.description} |")
        lines.append("")
    return "\n".join(lines)
