"""Kubernetes/kind manifest generation.

The reference embeds all of its YAML as heredocs inside the shell script
(kind config at kind-gpu-sim.sh:86-97, registry ConfigMap at :131-141,
plugin DaemonSets at :248-276 and :291-329).  Here manifests are built
as Python structures and serialized with PyYAML, so tests can assert on
them as data instead of grepping strings.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import yaml

from kind_tpu_sim import RESOURCE_BY_VENDOR
from kind_tpu_sim import topology as topo
from kind_tpu_sim.config import SimConfig

# Kubelet's device-plugin registration directory; the plugin DaemonSet
# must mount it to reach kubelet.sock (cf. kind-gpu-sim.sh:321-328).
KUBELET_DP_DIR = "/var/lib/kubelet/device-plugins"

PLUGIN_APP_LABEL = "tpu-sim-device-plugin"
PLUGIN_NAMESPACE = "kube-system"

# Host directory for simulator runtime state; the chaos subcommand
# writes device IDs into UNHEALTHY_FILE on a node to fail them.
SIM_STATE_DIR = "/var/run/tpu-sim"
UNHEALTHY_FILE = SIM_STATE_DIR + "/unhealthy"


class _ManifestDumper(yaml.SafeDumper):
    """SafeDumper that emits multiline strings as literal blocks (``|``)."""


def _str_representer(dumper: yaml.Dumper, data: str) -> yaml.Node:
    if "\n" in data:
        return dumper.represent_scalar(
            "tag:yaml.org,2002:str", data, style="|"
        )
    return dumper.represent_scalar("tag:yaml.org,2002:str", data)


_ManifestDumper.add_representer(str, _str_representer)


def to_yaml(obj: object) -> str:
    return yaml.dump(
        obj, Dumper=_ManifestDumper, sort_keys=False,
        default_flow_style=False,
    )


def kind_cluster_config(cfg: SimConfig) -> str:
    """kind Cluster config: 1 control-plane + N workers + registry mirror.

    Mirror patch matches the reference's containerdConfigPatches
    (kind-gpu-sim.sh:89-92); worker count is derived from the simulated
    slice instead of being hardcoded (:93-97).
    """
    doc = {
        "kind": "Cluster",
        "apiVersion": "kind.x-k8s.io/v1alpha4",
        "containerdConfigPatches": [
            (
                '[plugins."io.containerd.grpc.v1.cri".registry.mirrors.'
                f'"localhost:{cfg.registry_port}"]\n'
                f'  endpoint = ["http://{cfg.registry_name}:5000"]\n'
            )
        ],
        "nodes": [{"role": "control-plane"}]
        + [{"role": "worker"} for _ in range(cfg.workers)],
    }
    return to_yaml(doc)


def registry_configmap(cfg: SimConfig) -> str:
    """Standard local-registry-hosting ConfigMap (kind-gpu-sim.sh:131-141)."""
    doc = {
        "apiVersion": "v1",
        "kind": "ConfigMap",
        "metadata": {
            "name": "local-registry-hosting",
            "namespace": "kube-public",
        },
        "data": {
            "localRegistryHosting.v1": (
                f'host: "localhost:{cfg.registry_port}"\n'
                'help: "https://kind.sigs.k8s.io/docs/user/local-registry/"\n'
            ),
        },
    }
    return to_yaml(doc)


def containerd_hosts_toml(cfg: SimConfig) -> str:
    """Per-node registry mirror config (kind-gpu-sim.sh:122-125)."""
    return (
        f'[host."http://{cfg.registry_name}:5000"]\n'
        '  capabilities = ["pull", "resolve"]\n'
    )


def _taint_toleration(vendor: str) -> List[Dict[str, str]]:
    if vendor == "tpu":
        return [
            {
                "key": topo.TAINT_KEY,
                "operator": "Equal",
                "value": topo.TAINT_VALUE,
                "effect": topo.TAINT_EFFECT,
            }
        ]
    # reference taint: gpu=true:NoSchedule (kind-gpu-sim.sh:110)
    return [
        {
            "key": "gpu",
            "operator": "Equal",
            "value": "true",
            "effect": "NoSchedule",
        }
    ]


def _node_selector(vendor: str) -> Dict[str, str]:
    return {
        topo.LABEL_HARDWARE_TYPE: "tpu" if vendor == "tpu" else "gpu"
    }


def tpu_plugin_daemonset(cfg: SimConfig, image: str) -> str:
    """DaemonSet for the in-repo fake TPU device plugin.

    Structure follows the reference's NVIDIA deploy (kind-gpu-sim.sh:291-329)
    — node selector + toleration + privileged + kubelet socket-dir mount —
    but the image is our native C++ plugin and its behavior is driven by
    the slice-topology env block rather than FAIL_ON_INIT_ERROR.
    """
    s = cfg.slice
    # Slice-global identity shared by every worker; the plugin derives
    # the per-node TPU_WORKER_ID from NODE_NAME (plugin/src/
    # device_plugin.cc WorkerIdFromNodeName).
    w0 = s.worker_env(0)
    env = [
        {"name": "TPU_SIM_CHIPS", "value": str(s.chips_per_host)},
        {"name": "TPU_SIM_RESOURCE", "value": RESOURCE_BY_VENDOR["tpu"]},
        {"name": "TPU_SIM_ACCELERATOR", "value": s.spec.gke_type},
        {"name": "TPU_SIM_TOPOLOGY", "value": topo.format_topology(s.dims)},
        {
            "name": "TPU_SIM_ACCELERATOR_TYPE",
            "value": w0["TPU_ACCELERATOR_TYPE"],
        },
        {
            "name": "TPU_SIM_CHIPS_PER_HOST_BOUNDS",
            "value": w0["TPU_CHIPS_PER_HOST_BOUNDS"],
        },
        {"name": "TPU_SIM_HOST_BOUNDS", "value": w0["TPU_HOST_BOUNDS"]},
        {"name": "TPU_SIM_HOSTNAMES", "value": w0["TPU_WORKER_HOSTNAMES"]},
        {"name": "TPU_SIM_UNHEALTHY_FILE", "value": UNHEALTHY_FILE},
        {
            "name": "NODE_NAME",
            "valueFrom": {"fieldRef": {"fieldPath": "spec.nodeName"}},
        },
    ]
    if cfg.num_slices > 1:
        # Multislice: the plugin decomposes the node's global worker
        # index into (slice, local worker) and injects the MEGASCALE_*
        # cross-slice contract at Allocate. Hostnames become the full
        # cross-slice list; the plugin narrows to this slice's window.
        ms = cfg.multislice
        all_hosts = ms.hostnames()
        env = [e for e in env if e.get("name") != "TPU_SIM_HOSTNAMES"]
        env.extend([
            {"name": "TPU_SIM_HOSTNAMES",
             "value": ",".join(all_hosts)},
            {"name": "TPU_SIM_NUM_SLICES",
             "value": str(ms.num_slices)},
            {"name": "TPU_SIM_HOSTS_PER_SLICE",
             "value": str(s.num_hosts)},
            {"name": "TPU_SIM_MEGASCALE_COORDINATOR",
             "value": ms.megascale_env(0)[
                 "MEGASCALE_COORDINATOR_ADDRESS"]},
        ])
    doc = {
        "apiVersion": "apps/v1",
        "kind": "DaemonSet",
        "metadata": {
            "name": "tpu-sim-device-plugin",
            "namespace": PLUGIN_NAMESPACE,
            "labels": {"app": PLUGIN_APP_LABEL},
        },
        "spec": {
            "selector": {"matchLabels": {"app": PLUGIN_APP_LABEL}},
            "template": {
                "metadata": {"labels": {"app": PLUGIN_APP_LABEL}},
                "spec": {
                    "nodeSelector": _node_selector("tpu"),
                    "tolerations": _taint_toleration("tpu"),
                    "priorityClassName": "system-node-critical",
                    "containers": [
                        {
                            "name": "tpu-device-plugin",
                            "image": image,
                            "imagePullPolicy": "IfNotPresent",
                            "securityContext": {"privileged": True},
                            "env": env,
                            "volumeMounts": [
                                {
                                    "name": "device-plugin",
                                    "mountPath": KUBELET_DP_DIR,
                                },
                                {
                                    "name": "sim-state",
                                    "mountPath": SIM_STATE_DIR,
                                },
                            ],
                        }
                    ],
                    "volumes": [
                        {
                            "name": "device-plugin",
                            "hostPath": {
                                "path": KUBELET_DP_DIR,
                                "type": "DirectoryOrCreate",
                            },
                        },
                        {
                            "name": "sim-state",
                            "hostPath": {
                                "path": SIM_STATE_DIR,
                                "type": "DirectoryOrCreate",
                            },
                        },
                    ],
                },
            },
        },
    }
    return to_yaml(doc)


def gpu_plugin_daemonset(cfg: SimConfig, vendor: str, image: str) -> str:
    """ROCm/NVIDIA vendor-plugin DaemonSets (parity with :242-336)."""
    if vendor == "rocm":
        name = "amdgpu-device-plugin-daemonset"
        app = "amdgpu-device-plugin"
        container: Dict[str, object] = {
            "name": "amdgpu-dp-ds",
            "image": image,
            "imagePullPolicy": "IfNotPresent",
            "securityContext": {"privileged": True},
        }
        volumes: Optional[List[Dict[str, object]]] = None
    elif vendor == "nvidia":
        name = "nvidia-device-plugin-daemonset"
        app = "nvidia-device-plugin"
        container = {
            "name": "nvidia-device-plugin-ctr",
            "image": image,
            "securityContext": {"privileged": True},
            # lets the real plugin start with no NVML/GPU present
            # (kind-gpu-sim.sh:318-320)
            "env": [{"name": "FAIL_ON_INIT_ERROR", "value": "false"}],
            "volumeMounts": [
                {"name": "device-plugin", "mountPath": KUBELET_DP_DIR}
            ],
        }
        volumes = [
            {
                "name": "device-plugin",
                "hostPath": {
                    "path": KUBELET_DP_DIR,
                    "type": "DirectoryOrCreate",
                },
            }
        ]
    else:
        raise ValueError(f"no vendor plugin DaemonSet for {vendor!r}")

    pod_spec: Dict[str, object] = {
        "nodeSelector": _node_selector(vendor),
        "tolerations": _taint_toleration(vendor),
        "containers": [container],
    }
    if vendor == "nvidia":
        pod_spec["volumes"] = volumes
    doc = {
        "apiVersion": "apps/v1",
        "kind": "DaemonSet",
        "metadata": {"name": name, "namespace": PLUGIN_NAMESPACE},
        "spec": {
            "selector": {"matchLabels": {"app": app}},
            "template": {
                "metadata": {"labels": {"app": app}},
                "spec": pod_spec,
            },
        },
    }
    return to_yaml(doc)


def jax_multihost_manifest(cfg: SimConfig) -> str:
    """Multi-host JAX Services + StatefulSets from the slice topology.

    The reference has no analog (it hardcodes everything); round 1 shipped
    a static 2x8 ``pods/jax-multihost.yaml``.  This generator derives
    replicas, per-replica chip requests, and the coordinator hostname from
    ``cfg.slice`` so ``--topology=4x8`` (4 hosts) or a v4 ``2x2x4`` slice
    produce a working manifest without hand edits.  Hostnames follow
    :func:`kind_tpu_sim.topology.default_hostnames` (StatefulSet ordinal
    DNS under the headless ``tpu-sim`` service).

    Multislice (``cfg.num_slices > 1``): one Service + StatefulSet PER
    SLICE, each pinned to its slice's nodes via the slice-id label —
    every slice is its own jax.distributed world whose hostnames match
    :meth:`kind_tpu_sim.topology.MultiSlice.hostnames` (what the device
    plugin windows per slice at Allocate). Cross-slice identity arrives
    in the pods as the MEGASCALE_* env.
    """
    if cfg.num_slices > 1:
        docs = []
        for sid in range(cfg.num_slices):
            docs.append(_jax_world_manifest(
                cfg, name=f"jax-tpu-s{sid}",
                service=f"tpu-sim-s{sid}",
                extra_selector={topo.LABEL_SLICE_ID: str(sid)},
                slice_note=f"{sid}/{cfg.num_slices}"))
        return "\n".join(docs)
    return _jax_world_manifest(cfg, name="jax-tpu", service="tpu-sim",
                               extra_selector={}, slice_note=None)


def _jax_world_manifest(cfg: SimConfig, name: str, service: str,
                        extra_selector: Dict[str, str],
                        slice_note) -> str:
    from kind_tpu_sim.tpu_platform import (
        POD_JAX_REQUIREMENT,
        POD_SNIPPET,
    )

    s = cfg.slice
    replicas = s.num_hosts
    chips = s.chips_per_host
    coordinator = f"{name}-0.{service}.default.svc.cluster.local"
    payload = f"""\
pip install --quiet {POD_JAX_REQUIREMENT}
export XLA_FLAGS="--xla_force_host_platform_device_count={chips}"
export JAX_PLATFORMS=cpu
python - <<'PYEOF'
import os
import socket

{POD_SNIPPET}
import jax
import jax.numpy as jnp

ordinal = int(socket.gethostname().rsplit("-", 1)[-1])
replicas = int(os.environ.get("TPU_SIM_REPLICAS", "{replicas}"))
coordinator = "{coordinator}:8476"
print("process", ordinal, "of", replicas,
      "node worker id", os.environ.get("TPU_WORKER_ID"))
jax.distributed.initialize(
    coordinator_address=coordinator,
    num_processes=replicas,
    process_id=ordinal,
)
n = jax.device_count()
local = jax.local_device_count()
print("global devices:", n, "local:", local)
assert local == {chips}, local
assert n == {chips} * replicas, n
assert jax.devices()[0].platform == "tpu", jax.devices()[0].platform
print("PLATFORM OK:", jax.devices()[0].platform)

result = jax.pmap(
    lambda x: jax.lax.psum(x, "i"), axis_name="i"
)(jnp.arange(1.0, local + 1.0) + ordinal * local)
expected = n * (n + 1) / 2
assert float(result[0]) == expected, (result, expected)
print("GLOBAL PSUM OK:", float(result[0]),
      "over", n, "fake chips")
PYEOF
sleep 3600
"""
    service_doc = {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {"name": service},
        "spec": {
            "clusterIP": "None",
            "selector": {"app": name},
            "ports": [{"name": "coordinator", "port": 8476}],
        },
    }
    statefulset = {
        "apiVersion": "apps/v1",
        "kind": "StatefulSet",
        "metadata": {"name": name},
        "spec": {
            "serviceName": service,
            "replicas": replicas,
            "podManagementPolicy": "Parallel",
            "selector": {"matchLabels": {"app": name}},
            "template": {
                "metadata": {"labels": {"app": name}},
                "spec": {
                    "affinity": {
                        "podAntiAffinity": {
                            "requiredDuringSchedulingIgnoredDuringExecution": [
                                {
                                    "labelSelector": {
                                        "matchLabels": {"app": name}
                                    },
                                    "topologyKey": "kubernetes.io/hostname",
                                }
                            ]
                        }
                    },
                    "nodeSelector": {**_node_selector("tpu"),
                                     **extra_selector},
                    "tolerations": _taint_toleration("tpu"),
                    "containers": [
                        {
                            "name": "jax",
                            "image": (
                                "registry.access.redhat.com/ubi9/python-312"
                            ),
                            "command": ["sh", "-c"],
                            "args": [payload],
                            "env": [
                                {
                                    "name": "TPU_SIM_REPLICAS",
                                    "value": str(replicas),
                                }
                            ],
                            "resources": {
                                "limits": {
                                    RESOURCE_BY_VENDOR["tpu"]: chips
                                }
                            },
                        }
                    ],
                },
            },
        },
    }
    what = (f"slice {slice_note}" if slice_note
            else "the whole simulated slice")
    header = (
        f"# Multi-host JAX over {what} — the DCN tier.\n"
        "# GENERATED by kind_tpu_sim.manifests.jax_multihost_manifest for\n"
        f"# {s.accelerator_type} topology {topo.format_topology(s.dims)} "
        f"({replicas} hosts x {chips} chips).\n"
        "# Regenerate: kind-tpu-sim manifests jax-multihost "
        f"--accelerator={s.spec.gke_type} "
        f"--topology={topo.format_topology(s.dims)}\n"
        f"# CI greps for \"GLOBAL PSUM OK\" on {name}-0.\n"
    )
    return header + to_yaml(service_doc) + "---\n" + to_yaml(statefulset)


def plugin_app_label(vendor: str) -> str:
    return {
        "tpu": PLUGIN_APP_LABEL,
        "rocm": "amdgpu-device-plugin",
        "nvidia": "nvidia-device-plugin",
    }[vendor]
