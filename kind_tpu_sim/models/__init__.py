"""JAX workload models for the simulated TPU cluster.

transformer — flagship decoder LM (GQA, bf16, Megatron-TP specs)
decode      — KV-cache serving (prefill + fused greedy scan, snapshots)
quant       — int8 weight-only serving snapshot
checkpoint  — orbax checkpoint/resume
moe         — Switch-MoE expert-parallel MLP
"""

from kind_tpu_sim.models import transformer  # noqa: F401
