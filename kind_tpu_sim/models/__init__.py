"""JAX workload models for the simulated TPU cluster (filled by models.transformer)."""
