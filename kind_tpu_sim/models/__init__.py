"""JAX workload models for the simulated TPU cluster."""

from kind_tpu_sim.models import transformer  # noqa: F401
